"""Engine configuration — the session-level half of the reference's
two-layer config (SURVEY.md §6 "Config / flag system": session SQLConf keys
`spark.sparklinedata.*`; per-table options live in catalog.TableOptions).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np


@dataclass
class EngineConfig:
    # dtype policy: int64/float64 accumulators give exact parity (x64 is
    # emulated on TPU; measured acceptable for reduce-dominated kernels).
    long_dtype: object = np.int64
    double_dtype: object = np.float64
    enable_x64: bool = True

    # dense group-by budget: max total groups (dims × buckets product) the
    # dense table may hold before the query is declared non-rewritable
    # (SURVEY.md §8.4 #1). 2^22 groups × 8B ≈ 32 MB per aggregator.
    dense_group_budget: int = 1 << 22

    # theta sketch nominal-entries cap (k × groups × 8B of HBM)
    theta_k_cap: int = 1 << 14

    # host-side label-table cap per grouped NUMERIC dimension (the dense
    # id space materializes [size] labels at lowering time; this bounds
    # host memory, not the group space — the sparse path groups far past
    # the dense budget through the same per-dim id spaces)
    numeric_dim_label_budget: int = 1 << 22

    # sort-based sparse group-by (kernels.sparse_groupby), used when the
    # dense mixed-radix space exceeds dense_group_budget: initial compact
    # table size (adapts upward pow2 on overflow) and the hard ceiling of
    # PRESENT groups before the query is declared non-rewritable.
    sparse_group_cap: int = 1 << 15
    sparse_group_budget: int = 1 << 21
    # theta sketch width on the SPARSE path: [cap, k] tables (and their
    # [cap, parts*k] merge transients) must stay HBM-modest, so k is
    # clamped below the dense-path theta_k_cap. 256 -> ~6% RSE, the
    # sketch-shrink-under-memory-pressure tradeoff Druid also makes.
    sparse_theta_k_cap: int = 256
    # max [groups × radix] element count of dense per-group sketch state
    # (theta value tables, HLL register files). Past it a GroupBy takes
    # the sparse path (clamped sketch width) and other shapes decline
    # legibly — without this, a wide-group theta/HLL query allocates
    # K × k state long before K exceeds dense_group_budget (observed:
    # >100 GB at K ≈ 1M). 2^28 elements ≈ 2 GB int64 state keeps
    # legitimately-sized dense queries (e.g. hourly-year theta
    # timeseries) on the dense path
    dense_sketch_state_budget: int = 1 << 28
    # multi-chip sparse merge strategy: both run per-chip local
    # compaction as fan-out single-device programs over the resident
    # shards, then the host BROKER re-merges the D compact tables
    # (executor/sharding.py). "exchange" lets the broker table hold
    # D x sparse_group_budget present groups (capacity scales with chip
    # count, any key skew absorbed — there are no hash owners);
    # "gather" keeps the legacy global-budget contract (all groups must
    # fit one chip's table). A multi-host (DCN) mesh hands the whole
    # sparse program to GSPMD instead (global-budget capacity).
    sparse_merge: str = "exchange"

    # segments per device dispatch (flattened rows = batch × block_rows)
    max_segments_per_dispatch: int = 1 << 10

    # HBM residency budget (bytes) for device-cached column buffers across
    # all tables; least-recently-used columns evict when exceeded
    # (SURVEY.md §8.4 #4). None = unbounded (single-table dev default).
    hbm_budget_bytes: int | None = None

    # packed results: max non-empty groups shipped back per query in the
    # single-fetch compacted buffer (executor.packing). Queries whose
    # result exceeds this transparently re-run unpacked (slower transfer,
    # same answer).
    result_group_cap: int = 1 << 16

    # fallback-at-scale bounds (SURVEY.md §2 property 2 without the OOM):
    # parquet-backed tables whose footer row count exceeds
    # fallback_chunk_rows execute the fallback over streamed row-group
    # chunks (partial aggregation; bounded resident rows) instead of
    # materializing one frame; a chunked NON-aggregate result larger than
    # fallback_scan_row_cap refuses with a clear error instead of eating
    # host RAM.
    fallback_chunk_rows: int = 4_000_000
    fallback_chunk_batch_rows: int = 1 << 20
    fallback_scan_row_cap: int = 20_000_000
    # Correlation shapes the magic-set rewrite cannot serve (multi-
    # comparison conjuncts, outer refs outside WHERE, ORDER BY/LIMIT
    # inside the subquery) run a bounded nested loop instead: one
    # subquery execution per distinct outer key tuple, refused legibly
    # past this cap (SURVEY.md §2 property 2 "never an error").
    corr_nested_loop_cap: int = 2048
    # Chunked-fallback aggregate parallelism (fork pool over parquet row
    # groups): 0 = auto (min(8, cpu count)), 1 = sequential. The
    # reference's slow path was distributed Spark; this is its host-side
    # analog (SURVEY.md §2 L0, §4.4). The timeout bounds how long a
    # deadlocked fork worker can stall a query before the sequential
    # loop takes over (fork from a JAX-threaded parent can in principle
    # inherit a held allocator lock).
    # Interactive default (ADVICE round 5: a deadlocked fork pool used to
    # stall a query 15 min before the safe sequential retry; 45 s covers
    # the legitimate parallel case at bench scales). The dispatcher
    # additionally scales this UP with the estimated scan size
    # (fallback._parallel_timeout_s) so huge tables are not cut off.
    fallback_parallel_workers: int = 0
    fallback_parallel_timeout_s: float = 45.0
    # FROM/JOIN (SELECT ...) bodies route back through the engine's
    # statement executor (device path when rewritable). False keeps the
    # interpreter pure — bench.parity.pure_config() derives that oracle
    # config, and run_both uses it so the fallback side of every parity
    # check stays an independent pandas execution.
    fallback_derived_on_device: bool = True

    # shared-scan batch execution (executor.batch): compatible concurrent
    # agg queries against one table fuse into ONE device pass — each
    # segment window is read once and feeds N per-query (filter, agg)
    # legs, killing the per-query scan floor (PROFILE_CPU.json: ~65 ms
    # execute per query even for total_groups=1). batch_window_ms > 0
    # turns on the request coalescer: concurrent QueryRunner.execute()
    # callers wait up to this window and ride one fused dispatch
    # (docs/BATCH_EXECUTION.md). 0 = off (single-query behavior,
    # execute_batch() still available explicitly).
    batch_window_ms: float = 0.0
    # max logical queries per fused dispatch; larger batches split
    batch_max_queries: int = 16
    # numpy-platform ("cpu") shared scan: segments per chunk of the
    # chunked batch loop — each chunk is sliced once and fed to every
    # leg while cache-hot. Chunked float sums can differ from the
    # single-pass path in the last ulp (merge reorders addition).
    batch_chunk_segments: int = 64
    # numpy-platform batch parallelism across chunks (numpy releases the
    # GIL on large array ops): 0 = auto (min(4, cores)), 1 = serial
    batch_cpu_threads: int = 0

    # --- semantic result caching (executor.resultcache; docs/CACHING.md)
    # Tier 2: bounded LRU full-result cache keyed by (normalized query
    # JSON, table generation) — the broker result cache. Tier 1:
    # per-segment partial-aggregate cache keyed by (generation, segment
    # id, query template minus intervals) — the historical cache: a
    # repeated aggregate over a moving window recomputes only uncached
    # segments in one device pass and merges the rest host-side via the
    # aggregators' merge semantics. Both invalidate generationally on
    # ingest/DROP and clear with CLEAR DRUID CACHE. Off by default:
    # serving deployments opt in; benches/tests that measure raw compute
    # rely on every execution dispatching.
    result_cache_enabled: bool = False
    result_cache_max_bytes: int = 256 << 20
    segment_cache_enabled: bool = False
    segment_cache_max_bytes: int = 512 << 20
    # segments with fewer valid rows than this floor are recomputed
    # rather than cached (per-entry overhead beats the recompute win)
    segment_cache_min_rows: int = 256
    # max total per-segment state elements (segments x groups x agg
    # radix) the one-pass per-segment dispatch may allocate; plans past
    # it bypass tier 1 (the plain packed/partials path serves them)
    segment_cache_state_budget: int = 1 << 22

    # --- materialized rollup cubes (tpu_olap.cubes; docs/CUBES.md) ---
    # cube_rewrite_enabled gates the planner's aggregate-rewrite pass:
    # a covered aggregate is served by folding a registered cube's
    # stored partials instead of scanning the base table. Cubes only
    # exist once created (DDL / Engine.create_cube / advisor specs), so
    # the default-on flag costs one dict probe per query until then.
    cube_rewrite_enabled: bool = True
    # background maintainer: rebuild cubes whose base table's ingest
    # generation moved (stale cubes are never served either way — the
    # rewrite pass checks the generation first, mirroring the semantic
    # result cache's invalidation contract). False = refresh only via
    # REFRESH DRUID CUBES / CubeRegistry.refresh_now (deterministic for
    # tests and bench phases).
    cube_auto_refresh: bool = True
    cube_refresh_interval_s: float = 2.0
    # serve-time fold budget: max [groups x per-agg state radix]
    # elements the host fold may allocate (HLL register files / theta
    # tables scale it exactly like segment_cache_state_budget)
    cube_serve_state_budget: int = 1 << 22
    # serve-cost bailout: only serve from a cube when its (interval-
    # kept) row count is at least this factor smaller than the base
    # rows the query would scan after pruning. Measured on the SF10
    # bench (BENCH_CUBES.json): the pruned columnar scan moves ~130k
    # rows/ms where the host fold moves ~34k rows/ms, so break-even is
    # ~4x row reduction — 16 serves only clear wins and leaves
    # marginally-covered queries (manifest pruning already made them
    # fast) un-pessimized on the base path. <= 1 disables the check.
    cube_serve_min_reduction: float = 16.0

    # --- real-time ingest (segments/delta.py, segments/wal.py;
    # docs/INGEST.md) --- Engine.append lands rows in a mutable
    # in-memory delta scope, queryable immediately alongside sealed
    # segments; a WAL makes acknowledged appends crash-durable and a
    # background compactor seals deltas into time-partitioned segments.
    # ingest_wal_dir: directory for per-table write-ahead logs; None
    # disables durability (appends remain queryable, just not
    # replayable after a crash).
    ingest_wal_dir: str | None = None
    # fsync policy: "always" (fsync before acknowledging — the full
    # durability contract), "interval" (background flusher fsyncs every
    # ingest_wal_flush_interval_s; process crashes lose nothing, power
    # loss may lose the last interval), "never" (tests/benches).
    ingest_wal_fsync: str = "always"
    ingest_wal_flush_interval_s: float = 0.05
    # replay an existing WAL when a table is first registered in this
    # process (crash recovery); re-registering a live table always
    # RESETS its log instead (the appends belonged to the old data)
    ingest_wal_replay: bool = True
    # backpressure bound: max delta rows per table before appends shed
    # with 429 + Retry-After (ingest_retry_after_s); 0 = unbounded
    ingest_max_delta_rows: int = 1 << 20
    ingest_retry_after_s: float = 1.0
    # background compactor: seal deltas >= ingest_compact_rows into
    # time-partitioned sealed segments every ingest_compact_interval_s
    # (ingest-woken). False = compact only via Engine.compact_now
    # (deterministic for tests/benches).
    ingest_auto_compact: bool = True
    ingest_compact_rows: int = 1 << 16
    ingest_compact_interval_s: float = 2.0
    # --- durable sealed-segment store (segments/store.py;
    # docs/DURABILITY.md) --- checkpointed spill of the sealed scope as
    # checksummed columnar chunk files plus an atomically-swapped
    # manifest, so recovery replays only the WAL tail past the
    # checkpoint watermark instead of the whole append history.
    # ingest_store_dir: directory for per-table checkpoint stores; None
    # disables checkpointing (recovery replays the full WAL, the PR 13
    # behavior).
    ingest_store_dir: str | None = None
    # manifests retained per table (>= 2). The WAL truncates only
    # through the watermark of the OLDEST retained manifest (lag-one),
    # so a corrupt newest checkpoint always falls back to the previous
    # one with the covering WAL tail still on disk — a single corrupt
    # chunk or torn manifest never loses an acknowledged row.
    ingest_store_keep_manifests: int = 2
    # checkpoint automatically after every compaction (the durability
    # hook: seal -> spill -> manifest advance -> WAL truncate). False =
    # checkpoint only via Engine.checkpoint_now / CHECKPOINT DRUID
    # TABLE (deterministic for tests/benches).
    ingest_store_checkpoint_on_compact: bool = True

    # execution platform: "device" = default jax backend, "cpu" = numpy path
    platform: str = "device"

    # multi-chip: shard the segment axis across this many devices on a
    # 1-D 'chips' mesh (None/1 = single device) — jit + NamedSharding
    # over an INTERLEAVED segment->chip placement (executor/sharding.py:
    # segment i -> chip i mod D, so any time range load-balances and
    # windowed dispatch prunes per-chip working sets). The analog of the
    # reference's queryHistoricalServers fan-out (SURVEY.md §3.5 P2).
    num_shards: int | None = None

    # emit empty time buckets in timeseries results (Druid default)
    skip_empty_buckets: bool = False

    # reference's `allowTopN` / topN threshold guard (SURVEY.md §3.2
    # LimitTransform); used by the planner
    allow_topn: bool = True
    topn_max_threshold: int = 100_000

    # reference's allowCountDistinct: push COUNT(DISTINCT) as HLL
    allow_count_distinct: bool = True

    # session timezone for granularity math (reference: tz.id conf key)
    time_zone: str = "UTC"

    # cost model knobs (planner.cost). The four constants default to the
    # fitted values in planner/cost_calibration.json for the running
    # backend (tools/calibrate_cost.py writes them) and fall back to the
    # coarse built-ins; set explicitly to pin.
    cost_model_enabled: bool = True
    shard_merge_factor: float = 1.0
    cost_scan_ns_per_row_col: float | None = None
    cost_merge_ns_per_byte: float | None = None
    cost_collective_lat_us: float | None = None
    cost_gspmd_overhead: float | None = None
    # calibration/debug override: pin the dispatch strategy
    # ("historicals" | "broker"); None = cost-model decision
    force_strategy: str | None = None

    # failure detection / elastic recovery (SURVEY.md §6): device dispatch
    # retries after purging device caches; with a mesh, repeated failure
    # halves the shard count (the "chip loss -> re-shard the manifest"
    # analog of the reference's Spark task retry over DruidRDD partitions).
    dispatch_retries: int = 1
    degrade_shards_on_retry: bool = False
    # structural "never an error" guarantee (SURVEY.md §2 property 2):
    # after dispatch retries exhaust on a NON-structural failure, run the
    # pandas fallback instead of raising. Off = propagate (debugging).
    fallback_on_device_failure: bool = True
    # per-query deadline (seconds) on the device dispatch; on expiry the
    # engine falls back (the analog of the reference's task-kill -> HTTP
    # query abort, SURVEY.md §3.5). None = no deadline.
    query_deadline_s: float | None = None
    # fault hook: callable(stage: str, attempt: int) -> None, may raise
    # to inject a fault (None in production). A plain callable fires only
    # at the classic "dispatch" site; declaring a `stages` attribute
    # (None = all) opts into the generalized sites — host-transfer,
    # reprobe, ingest, batch-leg (resilience.faults.maybe_inject).
    fault_injector: object = None

    # --- stage-graph execution (docs/EXECUTION.md; docs/PERF_MODEL.md
    # "execution pipeline") ---
    # Every query runs as an explicit stage graph — plan -> enqueue ->
    # transfer -> finalize -> assemble — driven by executor/stages.py.
    # Each stage class has its own bounded pool (enqueue stays width 1:
    # the chip has one program queue; the others scale with this knob),
    # so the old two-phase split generalizes: enqueue holds
    # dispatch_lock only while the device program is fired, and the
    # transfer/finalize/assemble stages of different queries overlap.
    # pipeline_depth is GRAPH ADMISSION: it bounds how many per-query
    # stage graphs are in flight engine-wide (queued device work +
    # pinned result buffers stay within the HBM budget) while the
    # per-stage queues absorb bursts inside admitted graphs; 0 restores
    # the serialized behavior (dispatch_lock held across the whole
    # query, no graph admission).
    pipeline_depth: int = 4

    # --- resilience layer (tpu_olap.resilience; docs/RESILIENCE.md) ---
    # admission control: a bounded device-dispatch queue in front of
    # dispatch_lock. At most max_inflight_dispatches hold slots at once;
    # at most admission_queue_limit wait for one; the next caller (or a
    # caller whose query_deadline_s budget cannot cover the expected
    # queue wait) is shed immediately with QueryShed -> HTTP 429,
    # instead of piling onto the lock and timing out later.
    # max_inflight_dispatches <= 0 disables admission entirely.
    max_inflight_dispatches: int = 8
    admission_queue_limit: int = 64
    # circuit breaker: this many CONSECUTIVE terminal device failures
    # (dispatch retries exhausted, deadline hits, probe failures) trip
    # it open; while open, fallback-capable queries serve from the
    # interpreter (path="fallback_breaker") and the rest refuse with
    # BreakerOpen -> HTTP 503 + Retry-After. A background healer thread
    # probes the device every breaker_open_cooldown_s and closes the
    # breaker when the probe succeeds. <= 0 disables the breaker.
    breaker_failure_threshold: int = 5
    breaker_open_cooldown_s: float = 5.0

    # tracing (SURVEY.md §6): when set, each query dispatch runs under a
    # jax.profiler trace written beneath this directory; the history record
    # gets a "profile_trace" pointer. Opt-in — per-query profiler start/stop
    # costs milliseconds.
    profile_dir: str | None = None

    # observability (tpu_olap.obs): per-query span-tree tracing (obs.trace)
    # — on by default; the cost is two perf_counter() calls per stage.
    # trace_history_limit bounds the recent-trace ring served by
    # GET /debug/queries; traces slower than slow_query_ms also land in the
    # slow-query ring (slow_log_limit entries).
    tracing_enabled: bool = True
    trace_history_limit: int = 128
    slow_query_ms: float = 250.0
    slow_log_limit: int = 64
    # QueryRunner.history ring size: per-query observability records past
    # this evict oldest-first, so a long-running server's memory is flat.
    # Engine.counters() stays exact regardless — totals are maintained
    # incrementally at record time, never re-summed from (possibly
    # evicted) history.
    history_limit: int = 1024
    # structured event log (obs.events): engine-level occurrences
    # (query completion, breaker transitions, admission sheds, cache
    # clears, ingest) land in a bounded ring served by GET /debug/events;
    # event_log_path additionally appends each event as one JSON line to
    # that file (durable sink for a log pipeline). None = ring only.
    event_log_limit: int = 2048
    event_log_path: str | None = None
    # latency SLO (obs.slo): queries completing within slo_latency_ms
    # count good, others (and failures/sheds) bad; the burn-rate gauge
    # is bad_fraction over slo_window_s divided by the error budget
    # (1 - slo_target). Defaults mirror the bench north star
    # (BASELINE.md: every SSB query < 500 ms).
    slo_latency_ms: float = 500.0
    slo_target: float = 0.99
    slo_window_s: float = 3600.0
    # workload profiler (obs.workload; ISSUE 11): every completed-query
    # record folds into bounded per-template rolling stats — the demand
    # signal behind sys.query_templates, GET /debug/workload, and the
    # cube advisor. workload_max_templates bounds distinct templates
    # (least-recently-seen evicts); workload_latency_window bounds the
    # per-template latency ring the p50/p95/p99 derive from.
    workload_profile_enabled: bool = True
    workload_max_templates: int = 512
    workload_latency_window: int = 512
    # telemetry plane (obs.timeseries + obs.sentinel; ISSUE 17): a
    # periodic `telemetry` background graph on the stage scheduler
    # snapshots every counter/gauge family into bounded per-series
    # rings (sys.metrics_history / GET /debug/timeseries) and runs the
    # regression sentinel's drift checks. interval <= 0 disables the
    # graph; retention bounds each series ring.
    telemetry_enabled: bool = True
    telemetry_interval_s: float = 5.0
    telemetry_retention: int = 360
    # regression sentinel (obs.sentinel): EWMA + moment-sketch
    # baselines per query template and per stage; a served query
    # slower than max(floor, factor * baseline) after `min_samples`
    # warmup raises a latency_drift alert attributed to the stage
    # whose busy/wait moved most. Resource alerts (hbm_pressure,
    # eviction_thrash, wal_lag, breaker_open, admission_shed) fire on
    # the telemetry tick; an alert not re-confirmed for clear_after_s
    # clears. alerts surface as events + alerts_active{kind} +
    # sys.alerts + GET /debug/health.
    sentinel_enabled: bool = True
    sentinel_min_samples: int = 8
    sentinel_ewma_alpha: float = 0.2
    sentinel_latency_factor: float = 3.0
    sentinel_latency_floor_ms: float = 10.0
    sentinel_clear_after_s: float = 60.0
    sentinel_hbm_pressure: float = 0.90   # of hbm_budget_bytes
    sentinel_eviction_thrash: int = 32    # evictions per tick
    sentinel_wal_lag_records: int = 1024  # unsynced WAL frames
    sentinel_alert_limit: int = 256       # sys.alerts history ring
    # event-log JSONL sink rotation (obs.events): when the sink file
    # exceeds max_bytes it rotates to path.1 (shifting .1 -> .2 ...,
    # keeping `keep` rotated files) and emits a sink_rotate event.
    # 0 disables rotation (the pre-ISSUE-17 unbounded behavior).
    event_log_max_bytes: int = 64 * 1024 * 1024
    event_log_rotate_keep: int = 3

    # Pallas fused one-hot MXU reduce (kernels.pallas_reduce): "auto" uses
    # it on the TPU backend for eligible plans, "force" uses it everywhere
    # eligible (interpret mode off-TPU — for tests), "never" disables.
    use_pallas: str = "auto"
    # max dense group count the Pallas kernel serves — beyond this the
    # VPU compare cost (K·N comparisons across K-blocks) beats scatter
    pallas_group_cap: int = 8192
    # factorized lane packing (kernels.pallas_reduce.Factorization) cuts
    # the tile product to ~K*H, so factorizable layouts stay profitable
    # well past the direct cap: the measured on-chip win extends through
    # 2.1e13 FLOPs with no loss observed (PALLAS_SWEEP_TPU.json,
    # BENCH_TPU_SF20.json). Non-factorizable plans (min/max aggs, wide
    # H) keep the stricter cap above.
    pallas_group_cap_factorized: int = 65536
    pallas_rows_per_block: int = 1024
    # K-block tile height: group spaces wider than this tile over a second
    # grid axis ([KB, rb] one-hot per step instead of one [K, rb] tile)
    pallas_k_per_block: int = 1024
    # the one-hot reduce does K_pad*n*H_pad*2 FLOPs — O(K·n), the wrong
    # asymptotics for large K (docs/PERF_MODEL.md). Under "auto", plans
    # whose product exceeds this budget keep the XLA scatter kernel;
    # None = no cap (pre-A/B behavior; "force" always ignores the cap).
    # Default set from the on-chip A/B once the probe banks it.
    pallas_auto_flop_budget: float | None = None

    extra: dict = field(default_factory=dict)

    def apply_x64(self):
        if self.enable_x64:
            import jax
            jax.config.update("jax_enable_x64", True)
