"""Dimension lowering: DimensionSpec -> dense ids + labels.

Every grouped dimension becomes a dense id in [0, size): dictionary codes
for string dims (0 = null), value-offset for bounded numeric dims, and a
host-computed remap table for extraction dims (substring/regex/lookup over
the dictionary; timeFormat over bucket starts). This is what makes the
group key mixed-radix (kernels.groupby) and group tables mergeable across
chips without string exchange.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from tpu_olap.ir.dimensions import (DefaultDimensionSpec,
                                    ExtractionDimensionSpec,
                                    TimeFormatExtractionFn)
from tpu_olap.kernels.filtereval import _extraction_callable
from tpu_olap.kernels.timebucket import compile_time_format
from tpu_olap.segments.segment import ColumnType, TIME_COLUMN


class UnsupportedDimension(Exception):
    pass


@dataclass
class DimPlan:
    name: str          # output name
    size: int          # dense id space size
    labels: object     # np object array [size] of output values (None=null)
    source_col: str | None   # column whose array feeds ids() (None = time)
    kind: str          # "codes" | "numeric" | "remap" | "timeformat"
    remap_name: str | None = None   # ConstPool name for remap/offset consts
    offset_name: str | None = None
    time_plan: object = None        # BucketPlan for timeformat dims

    def ids(self, env, consts, xp):
        if self.kind == "codes":
            return env["cols"][self.source_col]
        if self.kind == "numeric":
            v = env["cols"][self.source_col]
            i = (v - consts[self.offset_name]).astype(xp.int32)
            # out-of-range/null -> slot 0 (null); executor masks via labels
            # np.int32 zero, not a Python 0: under x64 a weak scalar enters
            # jnp.where as i64 and Mosaic's scalar i64->i32 lowering
            # recurses when this runs inside the Pallas kernel
            z = np.int32(0)
            i = xp.where((i >= 1) & (i < self.size), i, z)
            nm = env["nulls"].get(self.source_col)
            if nm is not None:
                i = xp.where(nm, z, i)
            return i
        if self.kind == "remap":
            codes = env["cols"][self.source_col]
            return consts[self.remap_name][codes]
        if self.kind == "timeformat":
            fine = self.time_plan.ids(env["cols"][TIME_COLUMN], consts)
            return consts[self.remap_name][fine]
        raise AssertionError(self.kind)


def compile_dimension(spec, table, pool, t_min, t_max,
                      numeric_dim_budget=1 << 20) -> DimPlan:
    if isinstance(spec, DefaultDimensionSpec):
        col = spec.dimension
        if col not in table.schema:
            raise UnsupportedDimension(f"unknown dimension {col!r}")
        typ = table.schema[col]
        if typ is ColumnType.STRING:
            d = table.dictionaries[col]
            labels = np.empty(d.size + 1, object)
            labels[0] = None
            labels[1:] = d.values
            return DimPlan(spec.name, d.size + 1, labels, col, "codes")
        if typ is ColumnType.LONG:
            md = table.column_metadata([col])[col]
            lo, hi = md.get("min"), md.get("max")
            if lo is None:
                # empty table: single null slot
                return DimPlan(spec.name, 1, np.array([None], object), col,
                               "numeric", offset_name=pool.add(0, np.int64))
            size = int(hi - lo) + 2  # +1 null slot at 0
            if size > numeric_dim_budget:
                raise UnsupportedDimension(
                    f"numeric dimension {col!r} range {size} exceeds dense "
                    "budget")
            labels = np.empty(size, object)
            labels[0] = None
            labels[1:] = np.arange(lo, hi + 1)
            # ids = v - (lo - 1): value lo -> 1
            return DimPlan(spec.name, size, labels, col, "numeric",
                           offset_name=pool.add(int(lo) - 1, np.int64))
        raise UnsupportedDimension(
            f"cannot group by DOUBLE column {col!r} densely")
    if isinstance(spec, ExtractionDimensionSpec):
        col = spec.dimension
        ex = spec.extraction_fn
        if isinstance(ex, TimeFormatExtractionFn):
            if col != TIME_COLUMN:
                raise UnsupportedDimension(
                    "timeFormat extraction only on __time")
            plan, remap_name, values = compile_time_format(
                ex.format, ex.time_zone, t_min, t_max, pool)
            labels = np.array(values, object)
            return DimPlan(spec.name, len(values), labels, None,
                           "timeformat", remap_name=remap_name,
                           time_plan=plan)
        if col not in table.schema or table.schema[col] is not ColumnType.STRING:
            raise UnsupportedDimension(
                f"extraction dimension over non-string column {col!r}")
        d = table.dictionaries[col]
        fn = _extraction_callable(ex)
        extracted = [None] + [fn(v) for v in d.values]
        values = sorted({v for v in extracted if v is not None})
        index = {v: i + 1 for i, v in enumerate(values)}
        remap = np.asarray([0 if v is None else index[v] for v in extracted],
                           np.int32)
        labels = np.empty(len(values) + 1, object)
        labels[0] = None
        labels[1:] = values
        return DimPlan(spec.name, len(values) + 1, labels, col, "remap",
                       remap_name=pool.add(remap))
    raise UnsupportedDimension(f"unknown dimension spec {type(spec).__name__}")
