"""Dimension lowering: DimensionSpec -> dense ids + labels.

Every grouped dimension becomes a dense id in [0, size): dictionary codes
for string dims (0 = null), value-offset for bounded numeric dims, and a
host-computed remap table for extraction dims (substring/regex/lookup over
the dictionary; timeFormat over bucket starts). This is what makes the
group key mixed-radix (kernels.groupby) and group tables mergeable across
chips without string exchange.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from tpu_olap.ir.dimensions import (DefaultDimensionSpec,
                                    ExtractionDimensionSpec,
                                    TimeFormatExtractionFn)
from tpu_olap.kernels.filtereval import _extraction_callable
from tpu_olap.kernels.timebucket import compile_time_format
from tpu_olap.segments.segment import ColumnType, TIME_COLUMN


class UnsupportedDimension(Exception):
    pass


@dataclass
class DimPlan:
    name: str          # output name
    size: int          # dense id space size
    labels: object     # np object array [size] of output values (None=null)
    source_col: str | None   # column whose array feeds ids() (None = time)
    kind: str          # "codes" | "numeric" | "remap" | "timeformat"
    remap_name: str | None = None   # ConstPool name for remap/offset consts
    offset_name: str | None = None
    time_plan: object = None        # BucketPlan for timeformat dims
    # content hash for gather-needing kinds (remap/timeformat): the
    # runner precomputes these id streams ONCE per table as
    # device-resident derived columns (a per-dispatch 1-D gather over
    # every row costs ~60 ms on a v5e through XLA; resident ids cost
    # one HBM read like any column). ids() consumes the cached stream
    # when the env carries it under "\0d:<token>".
    cache_token: str | None = None

    @property
    def derived_name(self) -> str | None:
        return None if self.cache_token is None else "\0d:" + self.cache_token

    def ids(self, env, consts, xp):
        if self.cache_token is not None:
            hit = env["cols"].get("\0d:" + self.cache_token)
            if hit is not None:
                return hit
        if self.kind == "codes":
            return env["cols"][self.source_col]
        if self.kind == "numeric":
            v = env["cols"][self.source_col]
            i = (v - consts[self.offset_name]).astype(xp.int32)
            # out-of-range/null -> slot 0 (null); executor masks via labels
            # np.int32 zero, not a Python 0: under x64 a weak scalar enters
            # jnp.where as i64 and Mosaic's scalar i64->i32 lowering
            # recurses when this runs inside the Pallas kernel
            z = np.int32(0)
            i = xp.where((i >= 1) & (i < self.size), i, z)
            nm = env["nulls"].get(self.source_col)
            if nm is not None:
                i = xp.where(nm, z, i)
            return i
        if self.kind == "remap":
            codes = env["cols"][self.source_col]
            return consts[self.remap_name][codes]
        if self.kind == "timeformat":
            fine = self.time_plan.ids(env["cols"][TIME_COLUMN], consts)
            return consts[self.remap_name][fine]
        raise AssertionError(self.kind)


def compile_dimension(spec, table, pool, t_min, t_max,
                      numeric_dim_budget=1 << 20, vexprs=None) -> DimPlan:
    if isinstance(spec, DefaultDimensionSpec):
        col = spec.dimension
        if col not in table.schema:
            if vexprs and col in vexprs:
                # GROUP BY <integer expression>: a virtual column whose
                # id domain comes from interval arithmetic over its
                # inputs' min/max metadata (the expression itself is
                # materialized in the kernel env like any virtual)
                return _virtual_numeric_dim(spec, col, vexprs[col], table,
                                            pool, numeric_dim_budget)
            raise UnsupportedDimension(f"unknown dimension {col!r}")
        typ = table.schema[col]
        if typ is ColumnType.STRING:
            d = table.dictionaries[col]
            labels = np.empty(d.size + 1, object)
            labels[0] = None
            labels[1:] = d.values
            return DimPlan(spec.name, d.size + 1, labels, col, "codes")
        if typ is ColumnType.LONG:
            md = table.column_metadata([col])[col]
            lo = md.get("min")
            return _dense_numeric_plan(
                spec.name, col, None if lo is None else int(lo),
                None if lo is None else int(md["max"]),
                pool, numeric_dim_budget)
        raise UnsupportedDimension(
            f"cannot group by DOUBLE column {col!r} densely")
    if isinstance(spec, ExtractionDimensionSpec):
        col = spec.dimension
        ex = spec.extraction_fn
        if isinstance(ex, TimeFormatExtractionFn):
            if col != TIME_COLUMN:
                raise UnsupportedDimension(
                    "timeFormat extraction only on __time")
            plan, remap_name, values = compile_time_format(
                ex.format, ex.time_zone, t_min, t_max, pool,
                bucket_budget=numeric_dim_budget)
            labels = np.array(values, object)
            return DimPlan(spec.name, len(values), labels, None,
                           "timeformat", remap_name=remap_name,
                           time_plan=plan,
                           cache_token=_dim_token(
                               "tf", ex.format, ex.time_zone, t_min, t_max,
                               pool.consts[remap_name]))
        if col not in table.schema or table.schema[col] is not ColumnType.STRING:
            raise UnsupportedDimension(
                f"extraction dimension over non-string column {col!r}")
        d = table.dictionaries[col]
        fn = _extraction_callable(ex)
        extracted = [None] + [fn(v) for v in d.values]
        values = sorted({v for v in extracted if v is not None})
        index = {v: i + 1 for i, v in enumerate(values)}
        remap = np.asarray([0 if v is None else index[v] for v in extracted],
                           np.int32)
        labels = np.empty(len(values) + 1, object)
        labels[0] = None
        labels[1:] = values
        return DimPlan(spec.name, len(values) + 1, labels, col, "remap",
                       remap_name=pool.add(remap),
                       cache_token=_dim_token("rm", col, remap))
    raise UnsupportedDimension(f"unknown dimension spec {type(spec).__name__}")


def _dim_token(*parts) -> str:
    """Content hash over everything the derived id stream depends on:
    the remap table bytes + the source identity (+ time params for
    timeformat). Two queries with the same restriction share one cached
    stream; different restrictions cache separately."""
    import hashlib
    h = hashlib.sha1()
    for p in parts:
        if isinstance(p, np.ndarray):
            h.update(p.tobytes())
        else:
            h.update(repr(p).encode())
        h.update(b"\x1f")
    return h.hexdigest()[:16]


def _dense_numeric_plan(name, source_col, lo, hi, pool,
                        numeric_dim_budget) -> DimPlan:
    """Dense numeric dimension over values in [lo, hi] (slot 0 = null;
    ids = v - (lo - 1)). lo=None means an empty domain."""
    if lo is None:
        return DimPlan(name, 1, np.array([None], object), source_col,
                       "numeric", offset_name=pool.add(0, np.int64))
    size = hi - lo + 2  # +1 null slot at 0
    if size > numeric_dim_budget:
        raise UnsupportedDimension(
            f"numeric dimension {source_col!r} range {size} exceeds "
            "dense budget")
    labels = np.empty(size, object)
    labels[0] = None
    labels[1:] = np.arange(lo, hi + 1)
    return DimPlan(name, size, labels, source_col, "numeric",
                   offset_name=pool.add(lo - 1, np.int64))


def _virtual_numeric_dim(spec, col, expr, table, pool,
                         numeric_dim_budget) -> DimPlan:
    from tpu_olap.kernels.pallas_reduce import expr_int_bounds
    phys = sorted(expr.columns())
    for c in phys:
        if c not in table.schema:
            raise UnsupportedDimension(
                f"virtual dimension {col!r} references unknown {c!r}")
        if table.schema[c] is not ColumnType.LONG:
            raise UnsupportedDimension(
                f"virtual dimension {col!r} over non-LONG column {c!r}")
    md = table.column_metadata(set(phys))
    col_bounds = {}
    for c in phys:
        m = md.get(c, {})
        if m.get("min") is None:
            return _dense_numeric_plan(spec.name, col, None, None, pool,
                                       numeric_dim_budget)
        col_bounds[c] = (int(m["min"]), int(m["max"]))
    b = expr_int_bounds(expr, col_bounds)
    if b is None:
        raise UnsupportedDimension(
            f"virtual dimension {col!r} is not integer-bounded")
    return _dense_numeric_plan(spec.name, col, b[0], b[1], pool,
                               numeric_dim_budget)
