"""Executor — the analog of the reference's DruidRDD + Druid's query engine
(SURVEY.md §3.5, §8.2 steps 4/7): lowers a QuerySpec over a registered
table's segments to a jitted XLA program, caches compiled programs by query
*template* (literals stripped), keeps columns HBM-resident, and assembles
Druid-shaped results host-side. Multi-chip execution shards the segment axis
over a `NamedSharding` mesh with interleaved placement and merges per-chip
unfinalized partials at a host broker — or hands the whole program to
XLA's GSPMD partitioner (sharding.py; planner/cost.py picks).
"""

from tpu_olap.executor.config import EngineConfig  # noqa: F401
from tpu_olap.executor.runner import QueryRunner, QueryResult  # noqa: F401
