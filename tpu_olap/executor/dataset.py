"""Device-resident column cache: stacked [n_segments, block_rows] arrays.

The analog of Druid historicals' memory-mapped segments (SURVEY.md §2 L1):
columns are uploaded to the device once per table (lazily, per column) and
reused across queries — the Parquet→HBM streaming half of BASELINE.json:5.
Interval pruning is applied as a per-segment mask (columns stay resident;
masked segments cost compute but no transfer — the dense-scan tradeoff).
"""

from __future__ import annotations

import numpy as np

from tpu_olap.segments.segment import ColumnType, TableSegments, TIME_COLUMN

_I32_MIN, _I32_MAX = np.iinfo(np.int32).min + 1, np.iinfo(np.int32).max


class DeviceDataset:
    """Lazy per-column stacks for one table on one platform.

    With a mesh, stacks are padded to a multiple of the shard count with
    fully-invalid segments and device_put sharded on the segment axis —
    every chip holds 1/D of each column in its HBM.
    """

    def __init__(self, table: TableSegments, platform: str = "device",
                 mesh=None):
        self.table = table
        self.platform = platform
        self.mesh = mesh
        self._cols: dict[str, object] = {}
        self._nulls: dict[str, object] = {}
        self._valid = None
        n_seg = len(table.segments)
        if mesh is not None:
            from tpu_olap.executor.sharding import pad_segments
            n_seg = pad_segments(max(n_seg, 1), mesh.devices.size)
        self.shape = (n_seg, table.block_rows)

    def _put(self, arr: np.ndarray):
        if self.platform == "cpu":
            return arr
        import jax
        if self.mesh is not None:
            from tpu_olap.executor.sharding import shard_put
            return shard_put(arr, self.mesh)
        return jax.device_put(arr)

    def _stack(self, per_segment, dtype=None) -> np.ndarray:
        rows = [per_segment(s) for s in self.table.segments]
        fill = self.shape[0] - len(rows)
        if fill > 0:
            proto = rows[0] if rows else np.zeros(self.table.block_rows,
                                                  dtype or np.int32)
            rows = rows + [np.zeros_like(proto)] * fill
        return np.stack(rows)

    def _narrow_dtype(self, name: str):
        """int32 for LONG columns whose values all fit (per the segment
        manifest's column min/max) — halves HBM residency and scan
        bandwidth; sums still widen to the accumulator dtype on device.
        __time stays int64 (epoch millis exceed int32)."""
        if name == TIME_COLUMN or \
                self.table.schema.get(name) is not ColumnType.LONG:
            return None
        lo = hi = None
        for s in self.table.segments:
            mlo = s.meta.column_min.get(name)
            mhi = s.meta.column_max.get(name)
            if mlo is None:
                continue  # empty/all-null segment stores zero fill
            lo = mlo if lo is None else min(lo, mlo)
            hi = mhi if hi is None else max(hi, mhi)
        if lo is None or (lo >= _I32_MIN and hi <= _I32_MAX):
            return np.int32
        return None

    def col(self, name: str):
        if name not in self._cols:
            dt = self._narrow_dtype(name)
            get = (lambda s: s.columns[name]) if dt is None else \
                (lambda s: s.columns[name].astype(dt))
            self._cols[name] = self._put(self._stack(get))
        return self._cols[name]

    def null_mask(self, name: str):
        """None if the column has no nulls anywhere."""
        if name not in self._nulls:
            if any(name in s.null_masks for s in self.table.segments):
                zero = np.zeros(self.table.block_rows, bool)
                self._nulls[name] = self._put(
                    self._stack(lambda s: s.null_masks.get(name, zero)))
            else:
                self._nulls[name] = None
        return self._nulls[name]

    def valid(self):
        """[S, R] row-validity (padding rows/segments are False)."""
        if self._valid is None:
            r = np.arange(self.table.block_rows)
            self._valid = self._put(
                self._stack(lambda s: r < s.meta.n_valid, bool))
        return self._valid

    def segment_mask(self, kept_ids) -> np.ndarray:
        """Host-side [S] bool from pruned segment ids (device input arg)."""
        m = np.zeros(self.shape[0], bool)
        m[list(kept_ids)] = True
        return m

    def env(self, columns, null_cols):
        """Build the kernel env for the requested columns."""
        return {
            "cols": {c: self.col(c) for c in columns},
            "nulls": {c: m for c in null_cols
                      if (m := self.null_mask(c)) is not None},
        }

    def evict(self):
        self._cols.clear()
        self._nulls.clear()
        self._valid = None
