"""Device-resident column cache: stacked [n_segments, block_rows] arrays.

The analog of Druid historicals' memory-mapped segments (SURVEY.md §2 L1):
columns are uploaded to the device once per table (lazily, per column) and
reused across queries — the Parquet→HBM streaming half of BASELINE.json:5.
Interval pruning is applied as a per-segment mask (columns stay resident;
masked segments cost compute but no transfer — the dense-scan tradeoff).
"""

from __future__ import annotations

import numpy as np

from tpu_olap.segments.segment import TableSegments


class DeviceDataset:
    """Lazy per-column stacks for one table on one platform."""

    def __init__(self, table: TableSegments, platform: str = "device"):
        self.table = table
        self.platform = platform
        self._cols: dict[str, object] = {}
        self._nulls: dict[str, object] = {}
        self._valid = None
        n_seg = len(table.segments)
        self.shape = (n_seg, table.block_rows)

    def _put(self, arr: np.ndarray):
        if self.platform == "cpu":
            return arr
        import jax
        return jax.device_put(arr)

    def col(self, name: str):
        if name not in self._cols:
            stack = np.stack([s.columns[name] for s in self.table.segments])
            self._cols[name] = self._put(stack)
        return self._cols[name]

    def null_mask(self, name: str):
        """None if the column has no nulls anywhere."""
        if name not in self._nulls:
            if any(name in s.null_masks for s in self.table.segments):
                stack = np.stack([
                    s.null_masks.get(name,
                                     np.zeros(self.table.block_rows, bool))
                    for s in self.table.segments])
                self._nulls[name] = self._put(stack)
            else:
                self._nulls[name] = None
        return self._nulls[name]

    def valid(self):
        """[S, R] row-validity (padding rows are False)."""
        if self._valid is None:
            r = np.arange(self.table.block_rows)
            stack = np.stack([r < s.meta.n_valid
                              for s in self.table.segments])
            self._valid = self._put(stack)
        return self._valid

    def segment_mask(self, kept_ids) -> np.ndarray:
        """Host-side [S] bool from pruned segment ids (device input arg)."""
        m = np.zeros(self.shape[0], bool)
        m[list(kept_ids)] = True
        return m

    def env(self, columns, null_cols):
        """Build the kernel env for the requested columns."""
        return {
            "cols": {c: self.col(c) for c in columns},
            "nulls": {c: m for c in null_cols
                      if (m := self.null_mask(c)) is not None},
        }

    def evict(self):
        self._cols.clear()
        self._nulls.clear()
        self._valid = None
