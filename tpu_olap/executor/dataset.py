"""Device-resident column cache: stacked [n_segments, block_rows] arrays.

The analog of Druid historicals' memory-mapped segments (SURVEY.md §2 L1):
columns are uploaded to the device once per table (lazily, per column) and
reused across queries — the Parquet→HBM streaming half of BASELINE.json:5.
Interval pruning is applied as a per-segment mask (columns stay resident;
masked segments cost compute but no transfer — the dense-scan tradeoff).
"""

from __future__ import annotations

import threading
from collections import OrderedDict

import numpy as np

from tpu_olap.segments.segment import ColumnType, TableSegments, TIME_COLUMN


class HbmLedger:
    """LRU accounting of device-resident column buffers across every
    table the runner serves (SURVEY.md §8.4 #4: "v5e-8 HBM budget forces
    column discipline"). When an upload would exceed the budget, the
    least-recently-used unpinned buffers are evicted first; buffers the
    in-flight query needs are pinned for the duration of its env build.
    A single over-budget column still uploads (the query must run) —
    the budget bounds the cache, not one query's working set.

    In-flight result pinning (pipelined execution, docs/PERF_MODEL.md):
    between stage-1 enqueue and stage-2 transfer, a dispatch's output
    buffers live in HBM outside the column cache. `pin_inflight` counts
    those bytes toward the budget — so a concurrent query's env build
    evicts resident columns to make room rather than silently
    overcommitting HBM — and they are never themselves evictable (the
    transfer is about to read them). Mutations are internally locked:
    stage-2 unpins run lock-free with respect to dispatch_lock."""

    def __init__(self, budget_bytes: int | None, num_chips: int = 1):
        self.budget = budget_bytes
        self._entries: OrderedDict[tuple, tuple[int, object]] = \
            OrderedDict()  # key -> (nbytes, evict_fn)
        self._inflight: dict[tuple, int] = {}  # pinned result buffers
        self._mu = threading.RLock()
        self.bytes_in_use = 0
        self.evictions = 0
        # per-(chip, owner-class) attribution (ISSUE 17): under a mesh
        # every ledgered buffer is sharded EXACTLY 1/num_chips per chip
        # (DeviceDataset pads the segment axis to a multiple of D), so
        # a per-entry even split is the true placement, not an
        # estimate. Shares distribute any byte remainder to the lowest
        # chips deterministically, so per-chip sums always equal
        # bytes_in_use exactly. High-watermarks track ledger-managed
        # bytes at mutation time; external reporters (tier-1 cache
        # pins) are pulled live at breakdown time.
        self.num_chips = max(1, int(num_chips))
        self._chip_bytes = [0] * self.num_chips
        self._chip_hwm = [0] * self.num_chips
        self.high_watermark = 0
        self._by_chip_owner: dict[tuple, int] = {}
        self._external: dict = {}  # owner -> fn(num_chips) -> {chip: b}

    # ------------------------------------------- per-chip attribution

    @staticmethod
    def _owner_for(key) -> str:
        """Owner class of a ledger key: in-flight result pins, cube
        tables (catalog name `__cube_<name>`), or ordinary table
        columns (col/null/derived stacks)."""
        head = str(key[0]) if key else ""
        if head == "__inflight__":
            return "inflight"
        if head.startswith("__cube"):
            return "cube_tables"
        return "table_columns"

    def _shares(self, nbytes: int) -> list:
        q, r = divmod(int(nbytes), self.num_chips)
        return [q + (1 if c < r else 0) for c in range(self.num_chips)]

    def _account(self, key, nbytes: int, sign: int):
        """Incremental per-(chip, owner) bookkeeping; caller holds _mu
        and has already updated bytes_in_use."""
        owner = self._owner_for(key)
        for c, share in enumerate(self._shares(nbytes)):
            self._chip_bytes[c] += sign * share
            k = (c, owner)
            nb = self._by_chip_owner.get(k, 0) + sign * share
            if nb:
                self._by_chip_owner[k] = nb
            else:
                self._by_chip_owner.pop(k, None)
            if sign > 0 and self._chip_bytes[c] > self._chip_hwm[c]:
                self._chip_hwm[c] = self._chip_bytes[c]
        if sign > 0 and self.bytes_in_use > self.high_watermark:
            self.high_watermark = self.bytes_in_use

    def set_num_chips(self, num_chips: int):
        """Adopt the mesh's chip count (the runner learns it when the
        mesh is built, after the ledger exists) and re-attribute every
        live entry under the new split. Watermarks reset to the current
        totals — a high-watermark against a different chip count is not
        comparable."""
        d = max(1, int(num_chips))
        with self._mu:
            if d == self.num_chips:
                return
            self.num_chips = d
            self._chip_bytes = [0] * d
            self._by_chip_owner = {}
            for k, (nbytes, _fn) in self._entries.items():
                self._account(k, nbytes, +1)
            for k, nbytes in self._inflight.items():
                self._account(k, nbytes, +1)
            self._chip_hwm = list(self._chip_bytes)
            self.high_watermark = max(self.high_watermark,
                                      self.bytes_in_use)

    def register_external(self, owner: str, fn):
        """Register a live per-chip byte reporter folded into
        breakdown() under `owner` (tier-1 cache pins: the ResultCache
        owns those buffers and their eviction policy, so the ledger
        reports rather than manages them). fn(num_chips) -> {chip:
        bytes}."""
        with self._mu:
            self._external[owner] = fn

    def breakdown(self) -> dict:
        """{(chip, owner-class): bytes} — ledger-managed classes
        (table_columns, cube_tables, inflight) plus external reporters
        (cache_pins). The ledger-managed slice sums EXACTLY to
        bytes_in_use; the whole breakdown sums to total_bytes()."""
        with self._mu:
            out = dict(self._by_chip_owner)
            external = dict(self._external)
            d = self.num_chips
        for owner, fn in external.items():
            try:
                per_chip = fn(d) or {}
            except Exception:  # noqa: BLE001 — accounting, not serving
                continue
            for c, nbytes in per_chip.items():
                if nbytes:
                    k = (int(c), owner)
                    out[k] = out.get(k, 0) + int(nbytes)
        return out

    def total_bytes(self) -> int:
        """bytes_in_use plus external (cache-pin) bytes — what
        breakdown() sums to."""
        snap = self.breakdown()
        with self._mu:
            core = self.bytes_in_use
        return core + sum(b for (_c, o), b in snap.items()
                          if o in self._external)

    def watermarks(self) -> dict:
        """Ledger-managed high-watermarks, total and per chip."""
        with self._mu:
            return {"total": self.high_watermark,
                    "per_chip": list(self._chip_hwm)}

    @property
    def inflight_bytes(self) -> int:
        with self._mu:
            return sum(self._inflight.values())

    def touch(self, key):
        with self._mu:
            if key in self._entries:
                self._entries.move_to_end(key)

    def add(self, key, nbytes: int, evict_fn, pinned=frozenset()):
        with self._mu:
            if self.budget is not None:
                for k in list(self._entries):
                    if self.bytes_in_use + nbytes <= self.budget:
                        break
                    if k in pinned:
                        continue
                    n, fn = self._entries.pop(k)
                    self.bytes_in_use -= n
                    self._account(k, n, -1)
                    self.evictions += 1
                    fn()
            self._entries[key] = (nbytes, evict_fn)
            self.bytes_in_use += nbytes
            self._account(key, nbytes, +1)

    def pin_inflight(self, key, nbytes: int):
        """Account a dispatch's not-yet-transferred output buffers:
        counted in bytes_in_use (so later adds evict columns to stay
        within budget) but never in the evictable entry set."""
        with self._mu:
            self._inflight[key] = int(nbytes)
            self.bytes_in_use += int(nbytes)
            self._account(key, int(nbytes), +1)

    def unpin_inflight(self, key):
        with self._mu:
            n = self._inflight.pop(key, None)
            if n is not None:
                self.bytes_in_use -= n
                self._account(key, n, -1)

    def remove(self, key):
        with self._mu:
            e = self._entries.pop(key, None)
            if e is not None:
                self.bytes_in_use -= e[0]
                self._account(key, e[0], -1)

    def remove_table(self, table_name: str):
        with self._mu:
            for k in [k for k in self._entries if k[0] == table_name]:
                self.remove(k)


class DeviceDataset:
    """Lazy per-column stacks for one table on one platform.

    With a mesh, stacks are padded to a multiple of the chip count with
    fully-invalid segments, reordered into the INTERLEAVED placement
    (executor.sharding.placement: logical segment i → chip i mod D, so
    chip c's contiguous NamedSharding block holds its interleaved
    segments) and device_put sharded on the segment axis — every chip
    holds 1/D of each column in its HBM, and any contiguous time range
    of logical segments is load-balanced across all chips.

    Snapshot swaps (real-time appends, incremental compaction) pass the
    superseded dataset as `prev`: resident columns REBASE on device —
    rows of segments shared by identity with the old snapshot are
    gathered from the old device stack, and only delta-touched
    segments' rows upload (the ROADMAP 4c "appendable device buffers"
    fix: a small append no longer re-uploads every column).
    """

    def __init__(self, table: TableSegments, platform: str = "device",
                 mesh=None, ledger: HbmLedger | None = None, prev=None):
        self.table = table
        self.platform = platform
        self.mesh = mesh
        self.ledger = ledger
        self._cols: dict[str, object] = {}
        self._nulls: dict[str, object] = {}
        self._derived: dict[str, object] = {}
        self._valid = None
        n_seg = len(table.segments)
        self.to_place = self.to_logical = None
        self.n_chips = 1
        if mesh is not None:
            from tpu_olap.executor.sharding import (pad_segments,
                                                    placement)
            self.n_chips = mesh.devices.size
            n_seg = pad_segments(max(n_seg, 1), self.n_chips)
            self.to_place, self.to_logical = placement(n_seg,
                                                       self.n_chips)
        self.shape = (n_seg, table.block_rows)
        # incremental re-place (docs/INGEST.md): snapshot the old
        # dataset's resident stacks + placement so each column can
        # rebase device-side, uploading only changed segments' rows
        self._rebase = None
        self.rebased_cols = 0
        self.rebase_rows_uploaded = 0
        if (prev is not None and platform != "cpu"
                and prev.platform == platform
                and prev.table is not table
                and prev.table.block_rows == table.block_rows
                and prev.mesh is mesh):
            old_segs = prev.table.segments
            # uid equality, not object identity: incremental compaction
            # re-wraps untouched partitions in fresh Segment shells
            # around the SAME column arrays, carrying the uid over
            changed = [i for i, s in enumerate(table.segments)
                       if i >= len(old_segs)
                       or s.uid != old_segs[i].uid]
            # only worth the gather/scatter when most rows carry over
            if changed and len(changed) * 2 <= len(table.segments):
                self._rebase = {
                    "cols": dict(prev._cols),
                    "nulls": dict(prev._nulls),
                    "valid": prev._valid,
                    "old_place": prev.to_place,
                    "old_n": prev.shape[0],
                    "changed": changed,
                }

    def _put(self, arr: np.ndarray):
        if self.platform == "cpu":
            return arr
        import jax
        if self.mesh is not None:
            from tpu_olap.executor.sharding import shard_put
            return shard_put(arr, self.mesh)
        return jax.device_put(arr)

    def _place_pos(self, logical_ids, old: bool = False) -> np.ndarray:
        """Placed positions of logical segment ids (identity without a
        mesh; the interleave permutation with one)."""
        ids = np.asarray(logical_ids, np.int64)
        perm = self._rebase["old_place"] if old else self.to_place
        if perm is None:
            return ids
        return np.asarray(perm, np.int64)[ids]

    def _rebase_stack(self, old_arr, per_segment, target_dtype):
        """New device stack from the old snapshot's resident stack:
        unchanged segments gather from device memory, changed segments'
        rows upload. None when ineligible (dtype drift, no old stack) —
        the caller falls back to a full _stack + _put."""
        rb = self._rebase
        if rb is None or old_arr is None:
            return None
        if target_dtype is not None and \
                np.dtype(old_arr.dtype) != np.dtype(target_dtype):
            return None  # narrowed dtype widened: full re-upload
        import jax
        import jax.numpy as jnp
        changed = rb["changed"]
        n_new = len(self.table.segments)
        changed_set = set(changed)
        keep = [i for i in range(n_new)
                if i not in changed_set and i < rb["old_n"]]
        fresh = np.stack([per_segment(self.table.segments[i])
                          for i in changed])
        old_pos = self._place_pos(keep, old=True)
        new_pos_keep = self._place_pos(keep)
        new_pos_changed = self._place_pos(changed)
        S_new = self.shape[0]

        def build(old, up):
            base = jnp.zeros((S_new,) + old.shape[1:], old.dtype)
            if keep:
                base = base.at[new_pos_keep].set(old[old_pos])
            # explicit cast: jax promotes scatter values strictly, and a
            # weakly-typed uploaded block must not widen an int8 stack
            return base.at[new_pos_changed].set(up.astype(old.dtype))

        if self.mesh is not None:
            from tpu_olap.executor.sharding import shard_spec
            out = jax.jit(build,
                          out_shardings=shard_spec(self.mesh))(old_arr,
                                                               fresh)
        else:
            out = jax.jit(build)(old_arr, fresh)
        self.rebased_cols += 1
        self.rebase_rows_uploaded += int(fresh.size // max(
            1, self.table.block_rows)) * self.table.block_rows
        return out

    def _stack(self, per_segment, dtype=None) -> np.ndarray:
        rows = [per_segment(s) for s in self.table.segments]
        fill = self.shape[0] - len(rows)
        if fill > 0:
            proto = rows[0] if rows else np.zeros(self.table.block_rows,
                                                  dtype or np.int32)
            rows = rows + [np.zeros_like(proto)] * fill
        out = np.stack(rows)
        if self.to_logical is not None:
            # placement (chip-major) order: placed[p] = logical[tl[p]]
            out = out[self.to_logical]
        return out

    def _narrow_dtype(self, name: str):
        """Smallest int dtype (int8/int16/int32/int64) holding every
        value of a LONG column per the segment manifest's column min/max
        — 2-8x less HBM residency and scan bandwidth; sums still widen
        to the accumulator dtype on device. Usually a no-op cast: ingest
        already stores the narrowed dtype. __time stays int64 (epoch
        millis exceed int32)."""
        if name == TIME_COLUMN or \
                self.table.schema.get(name) is not ColumnType.LONG:
            return None
        from tpu_olap.segments.ingest import _int_dtype_for
        lo = hi = None
        for s in self.table.segments:
            mlo = s.meta.column_min.get(name)
            mhi = s.meta.column_max.get(name)
            if mlo is None:
                continue  # empty/all-null segment stores zero fill
            lo = mlo if lo is None else min(lo, mlo)
            hi = mhi if hi is None else max(hi, mhi)
        if lo is None:
            return np.dtype(np.int8)
        return _int_dtype_for(lo, hi)

    def _ledger_add(self, kind: str, name: str, arr, pinned):
        if self.ledger is None:
            return
        key = (self.table.name, kind, name)
        nbytes = int(np.prod(self.shape)) * np.dtype(arr.dtype).itemsize \
            if arr.dtype != bool else int(np.prod(self.shape))
        store = self._cols if kind == "col" else self._nulls
        self.ledger.add(key, nbytes, lambda: store.pop(name, None), pinned)

    def col(self, name: str, pinned=frozenset()):
        if name not in self._cols:
            dt = self._narrow_dtype(name)
            get = (lambda s: s.columns[name]) if dt is None else \
                (lambda s: s.columns[name].astype(dt, copy=False))
            arr = None
            if self._rebase is not None:
                arr = self._rebase_stack(
                    self._rebase["cols"].pop(name, None), get, dt)
            self._cols[name] = arr if arr is not None \
                else self._put(self._stack(get))
            self._ledger_add("col", name, self._cols[name], pinned)
        elif self.ledger is not None:
            self.ledger.touch((self.table.name, "col", name))
        return self._cols[name]

    def null_mask(self, name: str, pinned=frozenset()):
        """None if the column has no nulls anywhere."""
        if name not in self._nulls:
            if any(name in s.null_masks for s in self.table.segments):
                zero = np.zeros(self.table.block_rows, bool)
                get = lambda s: s.null_masks.get(name, zero)  # noqa: E731
                arr = None
                if self._rebase is not None:
                    arr = self._rebase_stack(
                        self._rebase["nulls"].pop(name, None), get, bool)
                self._nulls[name] = arr if arr is not None \
                    else self._put(self._stack(get))
                self._ledger_add("null", name, self._nulls[name], pinned)
            else:
                self._nulls[name] = None
        elif self.ledger is not None and self._nulls[name] is not None:
            self.ledger.touch((self.table.name, "null", name))
        return self._nulls[name]

    def derived(self, token: str, build, pinned=frozenset()):
        """Device-resident derived int32 stream [S, R] (precomputed dim
        ids: remap/timeformat gathers), computed ONCE per content token
        and reused across queries — a per-dispatch 6M-row 1-D gather is
        ~60 ms on a v5e through the XLA lowering; a resident stream costs
        one HBM read like any other column. Ledger-tracked (4 B/row) and
        evictable; an evicted stream transparently rebuilds. `pinned`
        must carry the in-flight query's working set so this add cannot
        evict buffers the same query is about to use."""
        if token not in self._derived:
            arr = build()
            self._derived[token] = arr
            if self.ledger is not None:
                key = (self.table.name, "derived", token)
                nbytes = int(np.prod(self.shape)) * 4
                self.ledger.add(key, nbytes,
                                lambda: self._derived.pop(token, None),
                                pinned)
        elif self.ledger is not None:
            self.ledger.touch((self.table.name, "derived", token))
        return self._derived[token]

    def valid(self):
        """[S, R] row-validity (padding rows/segments are False).
        Never ledgered: every query needs it and it is 1 byte/row.

        valid() is the LAST rebase consumer of a dispatch's working-set
        build (env() columns first, then validity — see
        QueryRunner._prepare_inner), so the rebase snapshot drops here:
        holding it longer would keep the superseded dataset's entire
        device-resident column set alive UNACCOUNTED (prev.evict()
        already released its ledger entries). Columns first touched by
        a later query pay a full upload instead — the hot columns (the
        ones being queried during ingest) are exactly the first
        dispatch's set."""
        if self._valid is None:
            r = np.arange(self.table.block_rows)
            get = lambda s: r < s.meta.n_valid  # noqa: E731
            arr = None
            if self._rebase is not None:
                arr = self._rebase_stack(self._rebase["valid"], get,
                                         bool)
            self._valid = arr if arr is not None \
                else self._put(self._stack(get, bool))
        self._rebase = None
        return self._valid

    def segment_mask(self, kept_ids) -> np.ndarray:
        """Host-side [S] bool from pruned LOGICAL segment ids (device
        input arg). Under a mesh the mask comes back in PLACEMENT order
        to match the placed column stacks."""
        m = np.zeros(self.shape[0], bool)
        m[list(kept_ids)] = True
        if self.to_logical is not None:
            m = m[self.to_logical]
        return m

    def env(self, columns, null_cols):
        """Build the kernel env for the requested columns. The whole
        working set is pinned while it builds so budget eviction cannot
        drop a column this same query is about to use."""
        pinned = frozenset(
            [(self.table.name, "col", c) for c in columns]
            + [(self.table.name, "null", c) for c in null_cols])
        return {
            "cols": {c: self.col(c, pinned) for c in columns},
            "nulls": {c: m for c in null_cols
                      if (m := self.null_mask(c, pinned)) is not None},
        }

    def resident_bytes(self) -> int:
        """Live device bytes this dataset holds right now: column/null/
        derived stacks plus the validity mask, via each buffer's own
        nbytes (jax Arrays and numpy arrays both expose it) — the
        per-table series behind `tpu_olap_device_bytes{table=...}`.
        list() snapshots tolerate the abandoned-deadline-thread
        concurrency the cache dicts already allow."""
        total = 0
        for store in (self._cols, self._nulls, self._derived):
            for arr in list(store.values()):
                total += int(getattr(arr, "nbytes", 0) or 0)
        if self._valid is not None:
            total += int(getattr(self._valid, "nbytes", 0) or 0)
        return total

    def evict(self):
        self._cols.clear()
        self._nulls.clear()
        self._derived.clear()
        self._valid = None
        self._rebase = None
        if self.ledger is not None:
            self.ledger.remove_table(self.table.name)
