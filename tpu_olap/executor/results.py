"""Host-side result finalization and assembly.

The analog of the reference's DruidQueryResultIterator + Spark-side final
aggregate (SURVEY.md §4.2's "JSON→row" hot loop) — except here the device
hands back small dense group tables, so assembly is O(groups), not O(rows):
finalize sketches, evaluate post-aggregations, apply having/limit, decode
dimension ids to values, and render Druid-wire-shaped records.
"""

from __future__ import annotations

import numpy as np

from tpu_olap.ir import aggregations as A
from tpu_olap.ir import having as H
from tpu_olap.ir import postaggs as P
from tpu_olap.kernels.hll import hll_estimate
from tpu_olap.kernels.theta import theta_estimate
from tpu_olap.utils import timeutil


def agg_specs_by_name(aggs) -> dict:
    out = {}
    for a in aggs:
        inner = a.aggregator if isinstance(a, A.FilteredAggregation) else a
        out[inner.name] = inner
    return out


def finalize_aggs(partials: dict, agg_plans, specs_by_name,
                  keep_raw=frozenset()) -> dict:
    """Device partials -> {name: np array [K]} of final values.

    Sketches are finalized to numeric estimates here (Druid finalizes at
    the broker; our 'broker' is this host step). min/max of empty groups
    become NaN (rendered as null); sums/counts of empty groups are 0.
    Theta aggregators named in `keep_raw` additionally retain their raw
    [K, k] hash tables (under "_theta_raw_<name>") for set-op post-aggs.
    """
    out = {"_rows": np.asarray(partials["_rows"])}
    for p in agg_plans:
        v = np.asarray(partials[p.name])
        if p.kind in ("count", "sum"):
            out[p.name] = v
            if f"_nn_{p.name}" in partials:
                out[f"_nn_{p.name}"] = np.asarray(partials[f"_nn_{p.name}"])
            continue
        if p.kind in ("min", "max"):
            nn = np.asarray(partials[f"_nn_{p.name}"])
            fv = v.astype(np.float64)
            out[p.name] = np.where(nn > 0, fv, np.nan)
            continue
        if p.kind == "hll":
            est = hll_estimate(v)
            spec = specs_by_name.get(p.name)
            if getattr(spec, "round", True):
                est = np.round(est)
            out[p.name] = est
            continue
        if p.kind == "theta":
            if p.name in keep_raw:
                out[f"_theta_raw_{p.name}"] = v
            out[p.name] = theta_estimate(v)
            continue
        raise AssertionError(p.kind)
    return out


def theta_raw_fields(post_aggs) -> set:
    """Theta aggregator names whose RAW sketch tables the post-aggs need
    (referenced from a set-op tree). Non-empty => the query must take an
    execution path that ships raw tables to the host (not the packed
    single-fetch path, which finalizes sketches on device)."""
    out: set = set()

    def walk(pa):
        if isinstance(pa, P.ThetaSketchSetOpPostAgg):
            for f in pa.fields:
                if isinstance(f, P.ThetaSketchSetOpPostAgg):
                    walk(f)
                else:
                    out.add(f.field_name)
        elif isinstance(pa, P.ThetaSketchEstimatePostAgg) and \
                pa.field is not None:
            walk(pa.field)
        elif isinstance(pa, P.ArithmeticPostAgg):
            for f in pa.fields:
                walk(f)

    for pa in post_aggs:
        walk(pa)
    return out


_THETA_EMPTY = 1.0  # kernels.theta.EMPTY


def _row_member(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Per-row membership: mask[i, j] = a[i, j] in b[i, :]. Both are
    row-sorted ascending with 1.0 empty-padding. One flat searchsorted
    via a row-offset trick — done in EXACT int64 space: unit hashes are
    2^-32 multiples (kernels.hashing.to_unit_float), so scaling by 2^32
    recovers the integer hash losslessly, and a 2^33 row stride keeps
    rows disjoint without eating mantissa bits (a float offset would
    merge adjacent hashes past ~2^20 rows)."""
    K = a.shape[0]
    ai = np.round(a * float(1 << 32)).astype(np.int64)
    bi = np.round(b * float(1 << 32)).astype(np.int64)
    off = np.arange(K, dtype=np.int64)[:, None] << 33
    bf = (bi + off).reshape(-1)
    af = (ai + off).reshape(-1)
    idx = np.searchsorted(bf, af)
    idx = np.minimum(idx, bf.size - 1)
    return (bf[idx] == af).reshape(a.shape)


def _theta_eval(pa, arrays):
    """Set-op tree -> (row-sorted table [K, k'], theta [K]). Leaves are
    raw theta tables; theta of a leaf is its k-th smallest when full,
    else 1.0 (exact mode)."""
    if isinstance(pa, P.ThetaSketchSetOpPostAgg):
        parts = [_theta_eval(f, arrays) for f in pa.fields]
        tables = [t for t, _ in parts]
        theta = np.minimum.reduce([th for _, th in parts])
        a = tables[0]
        if pa.func == "UNION":
            merged = np.sort(np.concatenate(tables, axis=-1), axis=-1)
            dup = np.concatenate(
                [np.zeros_like(merged[..., :1], bool),
                 merged[..., 1:] == merged[..., :-1]], axis=-1)
            merged = np.where(dup, _THETA_EMPTY, merged)
            return np.sort(merged, axis=-1), theta
        keep = np.ones(a.shape, bool)
        for b in tables[1:]:
            m = _row_member(a, b)
            keep &= m if pa.func == "INTERSECT" else ~m
        return np.sort(np.where(keep, a, _THETA_EMPTY), axis=-1), theta
    # leaf: FieldAccess to a theta aggregator's raw table
    raw = arrays.get(f"_theta_raw_{pa.field_name}")
    if raw is None:
        raise ValueError(
            f"theta set op references {pa.field_name!r}, which is not a "
            "theta sketch aggregator of this query")
    t = np.asarray(raw, np.float64)
    full = (t < _THETA_EMPTY).all(axis=-1)
    theta = np.where(full, t[..., -1], 1.0)
    return t, theta


def _theta_setop_estimate(pa, arrays) -> np.ndarray:
    table, theta = _theta_eval(pa, arrays)
    count = (table < theta[:, None]).sum(axis=-1)
    return count / np.maximum(theta, 1e-30)


def eval_post_aggs(arrays: dict, post_aggs) -> None:
    """Add post-aggregation outputs to `arrays` (in dependency order —
    Druid allows referencing earlier post-aggs)."""
    for pa in post_aggs:
        arrays[pa.name] = _eval_pa(pa, arrays)


def _eval_pa(pa, arrays):
    if isinstance(pa, P.FieldAccessPostAgg):
        return np.asarray(arrays[pa.field_name], np.float64)
    if isinstance(pa, P.ConstantPostAgg):
        return np.float64(pa.value)
    if isinstance(pa, P.ThetaSketchEstimatePostAgg) and pa.field is not None:
        return _theta_setop_estimate(pa.field, arrays)
    if isinstance(pa, P.ThetaSketchSetOpPostAgg):
        # referenced directly (no estimate wrapper): render its estimate
        return _theta_setop_estimate(pa, arrays)
    if isinstance(pa, (P.HyperUniqueCardinalityPostAgg,
                       P.ThetaSketchEstimatePostAgg)):
        # sketches are already finalized to numbers in finalize_aggs
        return np.asarray(arrays[pa.field_name], np.float64)
    if isinstance(pa, P.ArithmeticPostAgg):
        vals = [_eval_pa(f, arrays) for f in pa.fields]
        out = np.asarray(vals[0], np.float64)
        for v in vals[1:]:
            if pa.fn == "quotient":
                # true floating division (Druid's "quotient"): zero
                # denominator -> NaN, rendered as SQL NULL — used by
                # filtered AVG so an empty filtered group is NULL, not 0
                with np.errstate(divide="ignore", invalid="ignore"):
                    out = np.where(v != 0, out / np.where(v != 0, v, 1),
                                   np.nan)
            elif pa.fn == "/":
                # Druid arithmetic division yields 0 on division by zero
                with np.errstate(divide="ignore", invalid="ignore"):
                    out = np.where(v != 0, out / np.where(v != 0, v, 1), 0.0)
            elif pa.fn == "+":
                out = out + v
            elif pa.fn == "-":
                out = out - v
            elif pa.fn == "*":
                out = out * v
            else:
                raise ValueError(f"unknown post-agg fn {pa.fn!r}")
        return out
    raise ValueError(f"unknown post-agg {type(pa).__name__}")


def eval_having(spec, arrays: dict, dim_values: dict) -> np.ndarray:
    """HavingSpec -> bool mask over groups. dim_values: name -> object
    array of decoded dimension values per group row."""
    if isinstance(spec, H.GreaterThanHaving):
        return np.asarray(arrays[spec.aggregation], np.float64) > spec.value
    if isinstance(spec, H.LessThanHaving):
        return np.asarray(arrays[spec.aggregation], np.float64) < spec.value
    if isinstance(spec, H.EqualToHaving):
        return np.asarray(arrays[spec.aggregation], np.float64) == spec.value
    if isinstance(spec, H.DimSelectorHaving):
        vals = dim_values[spec.dimension]
        return np.asarray([v == spec.value for v in vals])
    if isinstance(spec, H.AndHaving):
        out = None
        for h in spec.having_specs:
            m = eval_having(h, arrays, dim_values)
            out = m if out is None else out & m
        return out
    if isinstance(spec, H.OrHaving):
        out = None
        for h in spec.having_specs:
            m = eval_having(h, arrays, dim_values)
            out = m if out is None else out | m
        return out
    if isinstance(spec, H.NotHaving):
        return ~eval_having(spec.having_spec, arrays, dim_values)
    raise ValueError(f"unknown having {type(spec).__name__}")


def render_value(v):
    """numpy -> plain-JSON value; NaN -> None (SQL null)."""
    if v is None:
        return None
    if isinstance(v, (np.floating, float)):
        f = float(v)
        return None if np.isnan(f) else f
    if isinstance(v, np.integer):
        return int(v)
    if isinstance(v, np.bool_):
        return bool(v)
    return v


def iso(ms: int) -> str:
    return timeutil.millis_to_iso(int(ms))
