"""Host-side result finalization and assembly.

The analog of the reference's DruidQueryResultIterator + Spark-side final
aggregate (SURVEY.md §4.2's "JSON→row" hot loop) — except here the device
hands back small dense group tables, so assembly is O(groups), not O(rows):
finalize sketches, evaluate post-aggregations, apply having/limit, decode
dimension ids to values, and render Druid-wire-shaped records.
"""

from __future__ import annotations

import numpy as np

from tpu_olap.ir import aggregations as A
from tpu_olap.ir import having as H
from tpu_olap.ir import postaggs as P
from tpu_olap.kernels.hll import hll_estimate
from tpu_olap.kernels.theta import theta_estimate
from tpu_olap.utils import timeutil


def agg_specs_by_name(aggs) -> dict:
    out = {}
    for a in aggs:
        inner = a.aggregator if isinstance(a, A.FilteredAggregation) else a
        out[inner.name] = inner
    return out


def finalize_aggs(partials: dict, agg_plans, specs_by_name) -> dict:
    """Device partials -> {name: np array [K]} of final values.

    Sketches are finalized to numeric estimates here (Druid finalizes at
    the broker; our 'broker' is this host step). min/max of empty groups
    become NaN (rendered as null); sums/counts of empty groups are 0.
    """
    out = {"_rows": np.asarray(partials["_rows"])}
    for p in agg_plans:
        v = np.asarray(partials[p.name])
        if p.kind in ("count", "sum"):
            out[p.name] = v
            if f"_nn_{p.name}" in partials:
                out[f"_nn_{p.name}"] = np.asarray(partials[f"_nn_{p.name}"])
            continue
        if p.kind in ("min", "max"):
            nn = np.asarray(partials[f"_nn_{p.name}"])
            fv = v.astype(np.float64)
            out[p.name] = np.where(nn > 0, fv, np.nan)
            continue
        if p.kind == "hll":
            est = hll_estimate(v)
            spec = specs_by_name.get(p.name)
            if getattr(spec, "round", True):
                est = np.round(est)
            out[p.name] = est
            continue
        if p.kind == "theta":
            out[p.name] = theta_estimate(v)
            continue
        raise AssertionError(p.kind)
    return out


def eval_post_aggs(arrays: dict, post_aggs) -> None:
    """Add post-aggregation outputs to `arrays` (in dependency order —
    Druid allows referencing earlier post-aggs)."""
    for pa in post_aggs:
        arrays[pa.name] = _eval_pa(pa, arrays)


def _eval_pa(pa, arrays):
    if isinstance(pa, P.FieldAccessPostAgg):
        return np.asarray(arrays[pa.field_name], np.float64)
    if isinstance(pa, P.ConstantPostAgg):
        return np.float64(pa.value)
    if isinstance(pa, (P.HyperUniqueCardinalityPostAgg,
                       P.ThetaSketchEstimatePostAgg)):
        # sketches are already finalized to numbers in finalize_aggs
        return np.asarray(arrays[pa.field_name], np.float64)
    if isinstance(pa, P.ArithmeticPostAgg):
        vals = [_eval_pa(f, arrays) for f in pa.fields]
        out = np.asarray(vals[0], np.float64)
        for v in vals[1:]:
            if pa.fn in ("/", "quotient"):
                # Druid arithmetic division yields 0 on division by zero
                with np.errstate(divide="ignore", invalid="ignore"):
                    out = np.where(v != 0, out / np.where(v != 0, v, 1), 0.0)
            elif pa.fn == "+":
                out = out + v
            elif pa.fn == "-":
                out = out - v
            elif pa.fn == "*":
                out = out * v
            else:
                raise ValueError(f"unknown post-agg fn {pa.fn!r}")
        return out
    raise ValueError(f"unknown post-agg {type(pa).__name__}")


def eval_having(spec, arrays: dict, dim_values: dict) -> np.ndarray:
    """HavingSpec -> bool mask over groups. dim_values: name -> object
    array of decoded dimension values per group row."""
    if isinstance(spec, H.GreaterThanHaving):
        return np.asarray(arrays[spec.aggregation], np.float64) > spec.value
    if isinstance(spec, H.LessThanHaving):
        return np.asarray(arrays[spec.aggregation], np.float64) < spec.value
    if isinstance(spec, H.EqualToHaving):
        return np.asarray(arrays[spec.aggregation], np.float64) == spec.value
    if isinstance(spec, H.DimSelectorHaving):
        vals = dim_values[spec.dimension]
        return np.asarray([v == spec.value for v in vals])
    if isinstance(spec, H.AndHaving):
        out = None
        for h in spec.having_specs:
            m = eval_having(h, arrays, dim_values)
            out = m if out is None else out & m
        return out
    if isinstance(spec, H.OrHaving):
        out = None
        for h in spec.having_specs:
            m = eval_having(h, arrays, dim_values)
            out = m if out is None else out | m
        return out
    if isinstance(spec, H.NotHaving):
        return ~eval_having(spec.having_spec, arrays, dim_values)
    raise ValueError(f"unknown having {type(spec).__name__}")


def render_value(v):
    """numpy -> plain-JSON value; NaN -> None (SQL null)."""
    if v is None:
        return None
    if isinstance(v, (np.floating, float)):
        f = float(v)
        return None if np.isnan(f) else f
    if isinstance(v, np.integer):
        return int(v)
    if isinstance(v, np.bool_):
        return bool(v)
    return v


def iso(ms: int) -> str:
    return timeutil.millis_to_iso(int(ms))
