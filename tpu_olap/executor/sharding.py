"""Multi-chip execution on `jax.jit` + `NamedSharding` (no shard_map).

The TPU-native replacement for the reference's direct-historical fan-out
(SURVEY.md §3.5 P2), rebuilt on the modern JAX API: columns are placed
ONCE with `jax.device_put(x, NamedSharding(mesh, P(AXIS)))` over an
INTERLEAVED segment→chip assignment (segment i → chip i mod D, the way a
Druid coordinator balances an interval's segments across historicals),
and group-reduce kernels compile with `jax.jit(..., out_shardings=...)`
so XLA's GSPMD partitioner inserts the cross-chip collectives the old
`jax.shard_map` code spelled by hand.

Two dense merge strategies (planner.cost picks per query, same decision
shape as the reference's broker-vs-direct-historicals choice):

- "historicals": the group key is EXTENDED by the owning chip id, the
  [D·K] partial table comes back sharded per chip (each chip's K-block
  lives in its own HBM — zero cross-chip traffic in the reduce), and a
  host-side **broker** step merges the D unfinalized partial tables
  with the exact algebra the segment cache and cube folds already share
  (kernels.groupby.merge_partials / partials_radix). One device fetch
  pulls every chip's shard concurrently, so stage-2 transfers overlap
  across chips.
- "broker": the WHOLE program is handed to GSPMD — plain group keys,
  replicated outputs, compiler-inserted psum/all-gather (the fan-out/
  merge is opaque, like Druid's broker).

Interleaved placement is what makes windowed dispatch prune PER-CHIP
working sets (docs/TPU_NOTES.md): a contiguous time range of logical
segments [lo, hi) lands on every chip as the LOCAL range
[lo//D, ceil(hi/D)), so the kernel reshapes [S, R] → [D, S/D, R]
(sharded on the chip axis) and dynamic-slices the local axis — each chip
reads only its ~(hi-lo)/D pruned segments, with no cross-chip data
movement and ONE compiled program per (template, local width).

High-cardinality sparse group-by fans out as true per-chip programs:
each chip's resident shard (an addressable single-device array — no
re-upload) runs the local sort/compact kernel, the D dispatches enqueue
asynchronously and fetch together, and the host broker re-merges the
compact tables (kernels.sparse_groupby.merge_sparse). Present-group
capacity under sparse_merge="exchange" is D × the per-chip budget —
the broker holds the union, so capacity scales with chip count.
"""

from __future__ import annotations

import functools

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXIS = "chips"
# legacy alias (pre-rewrite name for the 1-D segment axis)
DATA_AXIS = AXIS


def make_mesh(num_shards: int) -> Mesh:
    devs = jax.devices()
    if num_shards > len(devs):
        raise ValueError(
            f"num_shards={num_shards} exceeds {len(devs)} devices")
    return Mesh(np.array(devs[:num_shards]), (AXIS,))


def make_multihost_mesh(num_shards: int | None = None) -> Mesh:
    """Mesh over ALL processes' devices (call after
    jax.distributed.initialize on every host). Single-process callers
    get the same mesh make_mesh builds; multi-host callers get a 1-D
    chip axis spanning hosts — GSPMD's inserted collectives then ride
    ICI within a slice and DCN across slices with no code change."""
    devs = jax.devices()
    n = num_shards or len(devs)
    if n > len(devs):
        raise ValueError(f"num_shards={n} exceeds {len(devs)} devices")
    return Mesh(np.array(devs[:n]), (AXIS,))


def pad_segments(n_segments: int, num_shards: int) -> int:
    """Segments must split evenly across chips; padded blocks are fully
    invalid rows (valid mask False), so results are unaffected."""
    return -(-n_segments // num_shards) * num_shards


def placement(n_segments: int, num_shards: int):
    """(to_place, to_logical) permutations for the interleaved
    segment→chip assignment over a PADDED segment count.

    Logical segment i belongs to chip i mod D at local index i // D;
    the placed (device) order is chip-major, so chip c's contiguous
    NamedSharding block [c·S/D, (c+1)·S/D) holds exactly its
    interleaved segments. to_place[i] = placed position of logical i;
    to_logical[p] = logical id at placed position p."""
    per_chip = n_segments // num_shards
    logical = np.arange(n_segments, dtype=np.int64)
    to_place = (logical % num_shards) * per_chip + logical // num_shards
    to_logical = np.empty(n_segments, np.int64)
    to_logical[to_place] = logical
    return to_place.astype(np.int32), to_logical.astype(np.int32)


def chip_of(segment_id: int, num_shards: int) -> int:
    """Owning chip of a logical segment under interleaved placement."""
    return segment_id % num_shards


def is_multihost(mesh: Mesh) -> bool:
    """True when the mesh spans processes (DCN): remote shards are not
    addressable, so the host broker merge and per-chip fan-out cannot
    see them — those paths force the GSPMD spellings (replicated
    outputs, compiler-inserted collectives) instead."""
    return len({d.process_index for d in mesh.devices.flat}) > 1


def shard_spec(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P(AXIS))


def replicated_spec(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_put(arr: np.ndarray, mesh: Mesh):
    """Host array (PLACEMENT order on the leading axis) -> device array
    sharded per chip.

    Uses make_array_from_callback, the multi-host-correct formulation:
    each process materializes only the shards addressable on ITS devices
    (on a single host this degenerates to a plain sharded device_put).
    With a multi-host mesh every host feeds its local slice of the
    placed segment axis — no host ever holds the whole table."""
    sharding = shard_spec(mesh)
    return jax.make_array_from_callback(
        arr.shape, sharding, lambda idx: arr[idx])


def replicate_put(arr, mesh: Mesh):
    return jax.device_put(arr, replicated_spec(mesh))


def chip_shards(arr, mesh: Mesh) -> list:
    """Per-chip single-device views of a sharded (or replicated) array,
    in mesh order — each is a committed jax.Array resident on its chip,
    usable directly as an input to a per-device jitted program (the
    sparse fan-out path). No copies: the shards are the same buffers
    the sharded array owns."""
    by_dev = {s.device: s.data for s in arr.addressable_shards}
    return [by_dev[d] for d in mesh.devices.flat]


def chip_args(env, valid, seg_mask, consts, mesh: Mesh) -> list:
    """Per-chip (env, valid, seg_mask, consts) argument tuples for the
    sparse fan-out dispatch: sharded arrays split into their resident
    per-device shards, replicated consts resolve to each chip's copy —
    every piece is already on its chip, so the D single-device programs
    launch with zero re-upload."""
    D = mesh.devices.size
    cols = {k: chip_shards(v, mesh) for k, v in env["cols"].items()}
    nulls = {k: chip_shards(v, mesh) for k, v in env["nulls"].items()}
    vs = chip_shards(valid, mesh)
    ms = chip_shards(seg_mask, mesh)
    cs = {k: chip_shards(v, mesh) for k, v in consts.items()}
    return [({"cols": {k: cols[k][c] for k in cols},
              "nulls": {k: nulls[k][c] for k in nulls}},
             vs[c], ms[c], {k: cs[k][c] for k in cs})
            for c in range(D)]


def local_window(pruned_ids, num_shards: int, per_chip: int):
    """(lo_local, W_local) covering every pruned segment's LOCAL index
    on its chip, or None when windowing would not save >= 25% of the
    per-chip working set. Interleaved placement makes the local ranges
    near-identical across chips, so ONE (lo, W) serves all of them —
    the per-chip analog of QueryRunner._segment_window. `lo` is traced
    at dispatch, so a sliding interval of the same width re-uses the
    compiled program."""
    if not pruned_ids:
        return None
    lo = min(pruned_ids) // num_shards
    hi = max(pruned_ids) // num_shards + 1
    W = _next_pow2(hi - lo)
    W = min(W, per_chip)
    if 4 * W >= 3 * per_chip:
        return None
    return min(lo, per_chip - W), W


def _next_pow2(n: int) -> int:
    return 1 << max(0, int(n) - 1).bit_length() if n > 1 else 1


def _slice_local(a, D: int, per_chip: int, lo, W: int):
    """[D·per_chip, ...] -> [D·W, ...]: reshape the placed segment axis
    to (chip, local), dynamic-slice the LOCAL axis (unsharded — GSPMD
    slices per chip with no communication), flatten back."""
    a3 = a.reshape((D, per_chip) + a.shape[1:])
    w = jax.lax.dynamic_slice_in_dim(a3, lo, W, axis=1)
    return w.reshape((D * W,) + a.shape[1:])


def _window_env(env, valid, seg_mask, D, per_chip, lo, W):
    sl = functools.partial(_slice_local, D=D, per_chip=per_chip,
                           lo=lo, W=W)
    wenv = {"cols": {c: sl(a) for c, a in env["cols"].items()},
            "nulls": {c: sl(a) for c, a in env["nulls"].items()}}
    return wenv, sl(valid), sl(seg_mask)


def chip_extended_key(key, mask, D: int, blocks: int, K: int):
    """Group key extended by the owning chip (placement order: row
    block b belongs to chip b // blocks), so the [D·K] partial table
    shards per chip with zero cross-chip reduce traffic. THE one
    definition shared by the single-query mesh kernel and the fused
    batch legs — the key layout must never drift between them (a
    drift would silently de-synchronize fused-batch results from
    single-query mesh results)."""
    import jax.numpy as jnp

    r = mask.shape[0] // (D * blocks)
    chip = jnp.repeat(
        jnp.arange(D * blocks, dtype=jnp.int32) // jnp.int32(blocks), r)
    return chip * jnp.int32(K) + key.astype(jnp.int32)


def mesh_agg_kernel(plan, mesh: Mesh, per_chip: int, strategy: str,
                    win=None):
    """Jitted dense-aggregation program over the mesh.

    strategy "historicals": chip-extended group keys -> [D·K] partials,
    out_shardings=P(chips) so each chip's K-block stays in its own HBM
    (the host broker merges). strategy "broker": plain keys ->
    replicated [K] outputs, GSPMD inserts the cross-chip psum/
    all-gather merges. Both run the plan's GENERIC key_fn front half
    (the Pallas kernel is a single-chip program; under a mesh the
    shared jnp path serves every chip identically).

    Signature matches the single-device jit paths:
    fn(env, valid, seg_mask, consts[, lo_local]) with `lo_local` traced
    when a per-chip window is active."""
    from tpu_olap.kernels.groupby import group_reduce

    D = mesh.devices.size
    K = plan.total_groups
    W = win[1] if win is not None else per_chip
    historicals = strategy == "historicals"

    def body(env, valid, seg_mask, consts, lo=None):
        if lo is not None:
            env, valid, seg_mask = _window_env(env, valid, seg_mask,
                                               D, per_chip, lo, W)
        fenv, mask, key = plan.key_fn(env, valid, seg_mask, consts)
        if not historicals:
            return group_reduce(key, mask, fenv, plan.agg_plans, K,
                                consts)
        key2 = chip_extended_key(key, mask, D, W, K)
        return group_reduce(key2, mask, fenv, plan.agg_plans, D * K,
                            consts)

    out = shard_spec(mesh) if historicals else replicated_spec(mesh)
    if win is not None:
        return jax.jit(lambda e, v, m, c, lo: body(e, v, m, c, lo),
                       out_shardings=out)
    return jax.jit(lambda e, v, m, c: body(e, v, m, c),
                   out_shardings=out)


def mesh_mask_kernel(plan, mesh: Mesh):
    """Jitted row-mask program (scan/select/search): the plan's own
    kernel handed whole to GSPMD, outputs sharded per chip — the host
    fetch pulls each chip's rows concurrently, then inverse-permutes
    the placed segment axis back to logical order (runner side). On a
    multi-host mesh the mask replicates instead (every host must
    assemble the full row set)."""
    out = replicated_spec(mesh) if is_multihost(mesh) \
        else shard_spec(mesh)
    return jax.jit(plan.kernel, out_shardings=out)


def mesh_seg_partials_kernel(plan, mesh: Mesh, per_chip: int, W: int,
                             K: int):
    """Per-(chip, segment) partials in one mesh program: local-window
    slice, then the group key extends by the PLACED window position, so
    the [D·W·K] table comes back sharded per chip and splits into one
    mergeable partials dict per computed segment — the tier-1 cache
    shard entries the broker merge folds (docs/CACHING.md)."""
    import jax.numpy as jnp

    from tpu_olap.kernels.groupby import group_reduce

    D = mesh.devices.size

    def fn(env, valid, seg_mask, consts, lo):
        env, valid, seg_mask = _window_env(env, valid, seg_mask,
                                           D, per_chip, lo, W)
        fenv, mask, key = plan.key_fn(env, valid, seg_mask, consts)
        r = mask.shape[0] // (D * W)
        pos = jnp.repeat(jnp.arange(D * W, dtype=jnp.int32), r)
        key2 = pos * jnp.int32(K) + key.astype(jnp.int32)
        return group_reduce(key2, mask, fenv, plan.agg_plans, D * W * K,
                            consts)

    return jax.jit(fn, out_shardings=shard_spec(mesh))


def broker_merge(out: dict, agg_plans, num_shards: int) -> dict:
    """Host-side broker step: {name: [D·K, ...]} per-chip unfinalized
    partial tables -> one merged [K, ...] partials dict, folded with
    the exact merge algebra the segment cache and cube serves share
    (kernels.groupby.merge_partials: sums add, min/max fold, HLL
    registers max-merge, theta tables re-merge losslessly)."""
    from tpu_olap.kernels.groupby import merge_partials

    parts = []
    for d in range(num_shards):
        parts.append({
            name: np.asarray(v).reshape(
                (num_shards, -1) + np.asarray(v).shape[1:])[d]
            for name, v in out.items()})
    return functools.reduce(
        lambda a, b: merge_partials(a, b, agg_plans), parts)
