"""Multi-chip execution: segment-sharded data parallelism over a Mesh.

The TPU-native replacement for the reference's direct-historical fan-out
(SURVEY.md §3.5 P2): segments shard across chips on a 1-D 'data' mesh axis
(the analog of one partition per historical), each chip computes partial
dense group tables over its local segments, and the "Spark final merge
aggregate" becomes XLA collectives over ICI — psum for sums/counts, pmax/
pmin for extremes and HLL registers, an all_gather + fold for theta
sketches (SURVEY.md §3.6 transport summary; BASELINE.json:5 "partial
aggregates allreduce over ICI").

The dense group table is what makes this an allreduce instead of a hash
exchange: group ids are global (dictionary codes × calendar buckets), so no
chip ever needs another chip's rows — only its [K] table. High-cardinality
GROUP BY beyond the dense budget falls back (SURVEY.md §8.4 #1); a
hash-exchange path is future work.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_olap.kernels import theta as theta_mod

DATA_AXIS = "data"


def make_mesh(num_shards: int) -> Mesh:
    devs = jax.devices()
    if num_shards > len(devs):
        raise ValueError(
            f"num_shards={num_shards} exceeds {len(devs)} devices")
    return Mesh(np.array(devs[:num_shards]), (DATA_AXIS,))


def merge_collective(out: dict, agg_plans, axis: str = DATA_AXIS) -> dict:
    """Merge per-chip partial aggregates across the mesh axis — the same
    ops as kernels.groupby.merge_partials, as collectives."""
    merged = {"_rows": jax.lax.psum(out["_rows"], axis)}
    for p in agg_plans:
        v = out[p.name]
        if p.kind in ("count", "sum"):
            merged[p.name] = jax.lax.psum(v, axis)
        elif p.kind == "min":
            merged[p.name] = jax.lax.pmin(v, axis)
        elif p.kind in ("max", "hll"):
            merged[p.name] = jax.lax.pmax(v, axis)
        elif p.kind == "theta":
            g = jax.lax.all_gather(v, axis)  # [D, K, k]
            acc = g[0]
            for i in range(1, g.shape[0]):
                acc = theta_mod.theta_merge(acc, g[i], jnp)
            merged[p.name] = acc
        else:
            raise AssertionError(p.kind)
        nn = f"_nn_{p.name}"
        if nn in out:
            merged[nn] = jax.lax.psum(out[nn], axis)
    return merged


def sharded_kernel(plan, mesh: Mesh):
    """Wrap a PhysicalPlan kernel in shard_map over the segment axis.

    Inputs arrive sharded on their leading (segment) dim; consts are
    replicated; outputs are replicated merged tables (every chip holds the
    final answer — the host reads one copy).
    """
    kernel = plan.kernel
    agg_plans = plan.agg_plans
    is_mask = plan.kind == "mask"

    def local(env, valid, seg_mask, consts):
        out = kernel(env, valid, seg_mask, consts)
        if is_mask:
            return out  # row masks stay sharded; host gathers per shard
        return merge_collective(out, agg_plans)

    def specs_like(env):
        return {
            "cols": {k: P(DATA_AXIS) for k in env["cols"]},
            "nulls": {k: P(DATA_AXIS) for k in env["nulls"]},
        }

    def run(env, valid, seg_mask, consts):
        f = jax.shard_map(
            local, mesh=mesh,
            in_specs=(specs_like(env), P(DATA_AXIS), P(DATA_AXIS),
                      jax.tree.map(lambda _: P(), consts)),
            out_specs=(jax.tree.map(lambda _: P(DATA_AXIS), {"mask": 0})
                       if is_mask else P()),
            # the theta merge (all_gather + fold) is replicated by
            # construction but defeats static replication inference
            check_vma=False,
        )
        return f(env, valid, seg_mask, consts)

    return run


def sharded_sparse_kernel(kernel, plan, mesh: Mesh, cap: int):
    """Sparse (sort-based) group-by over the mesh: each chip reduces its
    local segments to a compacted [cap] table, tables all_gather over ICI
    ([D, cap] is small), and every chip re-merges by key — the sparse
    analog of merge_collective (SURVEY.md §3.5 P2 with compaction standing
    in for the dense-table allreduce)."""
    from tpu_olap.kernels.sparse_groupby import merge_sparse

    agg_plans = plan.agg_plans

    def local(env, valid, seg_mask, consts):
        out = kernel(env, valid, seg_mask, consts)
        gathered = {k: jax.lax.all_gather(v, DATA_AXIS)
                    for k, v in out.items()}
        n = mesh.devices.size
        parts = [{k: gathered[k][d] for k in out} for d in range(n)]
        return merge_sparse(parts, agg_plans, cap, jnp)

    def specs_like(env):
        return {
            "cols": {k: P(DATA_AXIS) for k in env["cols"]},
            "nulls": {k: P(DATA_AXIS) for k in env["nulls"]},
        }

    def run(env, valid, seg_mask, consts):
        f = jax.shard_map(
            local, mesh=mesh,
            in_specs=(specs_like(env), P(DATA_AXIS), P(DATA_AXIS),
                      jax.tree.map(lambda _: P(), consts)),
            out_specs=P(),
            check_vma=False,  # replicated by construction post-gather
        )
        return f(env, valid, seg_mask, consts)

    return run


def shard_put(arr: np.ndarray, mesh: Mesh):
    """Host array -> device array sharded on the leading axis."""
    return jax.device_put(arr, NamedSharding(mesh, P(DATA_AXIS)))


def replicate_put(arr, mesh: Mesh):
    return jax.device_put(arr, NamedSharding(mesh, P()))


def pad_segments(n_segments: int, num_shards: int) -> int:
    """Segments must split evenly across shards; padded blocks are fully
    invalid rows (valid mask False), so results are unaffected."""
    return -(-n_segments // num_shards) * num_shards
