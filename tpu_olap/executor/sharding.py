"""Multi-chip execution: segment-sharded data parallelism over a Mesh.

The TPU-native replacement for the reference's direct-historical fan-out
(SURVEY.md §3.5 P2): segments shard across chips on a 1-D 'data' mesh axis
(the analog of one partition per historical), each chip computes partial
dense group tables over its local segments, and the "Spark final merge
aggregate" becomes XLA collectives over ICI — psum for sums/counts, pmax/
pmin for extremes and HLL registers, an all_gather + fold for theta
sketches (SURVEY.md §3.6 transport summary; BASELINE.json:5 "partial
aggregates allreduce over ICI").

The dense group table is what makes this an allreduce instead of a hash
exchange: group ids are global (dictionary codes × calendar buckets), so no
chip ever needs another chip's rows — only its [K] table. High-cardinality
GROUP BY beyond the dense budget takes the sparse (sort-based) path, whose
multi-chip merge is a **hash exchange** (SURVEY.md §3.5 last row, §8.4 #1):
each chip compacts its local groups, entries route to a key-hash owner chip
over an ICI all_to_all, and each owner merges only its own keys — so
present-group capacity scales with chip count (D × per-chip budget when
keys distribute) and per-chip merge work stays O(global/D), unlike the
legacy gather-everything strategy (sharded_sparse_gather_kernel, kept as
EngineConfig.sparse_merge="gather").
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_olap.kernels import theta as theta_mod

DATA_AXIS = "data"


def make_mesh(num_shards: int) -> Mesh:
    devs = jax.devices()
    if num_shards > len(devs):
        raise ValueError(
            f"num_shards={num_shards} exceeds {len(devs)} devices")
    return Mesh(np.array(devs[:num_shards]), (DATA_AXIS,))


def merge_collective(out: dict, agg_plans, axis: str = DATA_AXIS) -> dict:
    """Merge per-chip partial aggregates across the mesh axis — the same
    ops as kernels.groupby.merge_partials, as collectives."""
    merged = {"_rows": jax.lax.psum(out["_rows"], axis)}
    for p in agg_plans:
        v = out[p.name]
        if p.kind in ("count", "sum"):
            merged[p.name] = jax.lax.psum(v, axis)
        elif p.kind == "min":
            merged[p.name] = jax.lax.pmin(v, axis)
        elif p.kind in ("max", "hll"):
            merged[p.name] = jax.lax.pmax(v, axis)
        elif p.kind == "theta":
            g = jax.lax.all_gather(v, axis)  # [D, K, k]
            acc = g[0]
            for i in range(1, g.shape[0]):
                acc = theta_mod.theta_merge(acc, g[i], jnp)
            merged[p.name] = acc
        else:
            raise AssertionError(p.kind)
        nn = f"_nn_{p.name}"
        if nn in out:
            merged[nn] = jax.lax.psum(out[nn], axis)
    return merged


def sharded_kernel(plan, mesh: Mesh):
    """Wrap a PhysicalPlan kernel in shard_map over the segment axis.

    Inputs arrive sharded on their leading (segment) dim; consts are
    replicated; outputs are replicated merged tables (every chip holds the
    final answer — the host reads one copy).
    """
    kernel = plan.kernel
    agg_plans = plan.agg_plans
    is_mask = plan.kind == "mask"

    def local(env, valid, seg_mask, consts):
        out = kernel(env, valid, seg_mask, consts)
        if is_mask:
            return out  # row masks stay sharded; host gathers per shard
        return merge_collective(out, agg_plans)

    def specs_like(env):
        return {
            "cols": {k: P(DATA_AXIS) for k in env["cols"]},
            "nulls": {k: P(DATA_AXIS) for k in env["nulls"]},
        }

    def run(env, valid, seg_mask, consts):
        f = jax.shard_map(
            local, mesh=mesh,
            in_specs=(specs_like(env), P(DATA_AXIS), P(DATA_AXIS),
                      jax.tree.map(lambda _: P(), consts)),
            out_specs=(jax.tree.map(lambda _: P(DATA_AXIS), {"mask": 0})
                       if is_mask else P()),
            # the theta merge (all_gather + fold) is replicated by
            # construction but defeats static replication inference
            check_vma=False,
        )
        return f(env, valid, seg_mask, consts)

    return run


def sharded_sparse_gather_kernel(kernel, plan, mesh: Mesh, cap: int):
    """Legacy sparse merge: each chip reduces its local segments to a
    compacted [cap] table, tables all_gather over ICI, and every chip
    re-merges the full [D, cap] concatenation. Simple and fine for small
    D·cap; superseded by the hash exchange below for scale (every chip
    pays O(D·cap) transfer + re-sort, and cap must hold ALL groups)."""
    from tpu_olap.kernels.sparse_groupby import merge_sparse

    agg_plans = plan.agg_plans

    def local(env, valid, seg_mask, consts):
        out = kernel(env, valid, seg_mask, consts)
        gathered = {k: jax.lax.all_gather(v, DATA_AXIS)
                    for k, v in out.items()}
        n = mesh.devices.size
        parts = [{k: gathered[k][d] for k in out} for d in range(n)]
        return merge_sparse(parts, agg_plans, cap, jnp)

    def specs_like(env):
        return {
            "cols": {k: P(DATA_AXIS) for k in env["cols"]},
            "nulls": {k: P(DATA_AXIS) for k in env["nulls"]},
        }

    def run(env, valid, seg_mask, consts):
        f = jax.shard_map(
            local, mesh=mesh,
            in_specs=(specs_like(env), P(DATA_AXIS), P(DATA_AXIS),
                      jax.tree.map(lambda _: P(), consts)),
            out_specs=P(),
            check_vma=False,  # replicated by construction post-gather
        )
        return f(env, valid, seg_mask, consts)

    return run


def bucket_cap(cap_local: int, num_shards: int) -> int:
    """Send-bucket slots per destination chip: expected load is
    cap_local/D under a uniform key hash; 2x headroom absorbs skew."""
    return max(64, -(-2 * cap_local // num_shards))


def _owner_of(keys, num_shards: int, jnp):
    """Key-hash owner chip (Fibonacci multiplicative hash over the int64
    mixed-radix key; the multiplier is 2^64/φ as a signed int64)."""
    h = keys * jnp.int64(-7046029254386353131)
    h = (h >> jnp.int64(33)) & jnp.int64(0x7FFFFFFF)
    return (h % jnp.int64(num_shards)).astype(jnp.int32)


def sharded_sparse_exchange_kernel(kernel, plan, mesh: Mesh,
                                   cap_local: int, cap_owner: int):
    """Hash-exchange sparse merge (SURVEY.md §3.5 last row; §8.4 #1;
    PAPERS.md "partial partial aggregates" shape):

      1. each chip compacts its local rows to a sorted [cap_local] group
         table (the pre-aggregation — row counts never cross ICI);
      2. every entry routes to owner = hash(key) % D: entries scatter
         into a [D, B] send buffer (B = bucket_cap) and swap via ONE
         lax.all_to_all over ICI — each chip transfers O(cap_local), not
         O(D·cap) like the gather strategy;
      3. each owner merges only its own keys into a [cap_owner] table —
         per-chip merge work is O(global/D), and total capacity is
         D × cap_owner: present-group cardinality scales with chip count.

    Outputs stay sharded on the owner axis (the host reads [D·cap_owner]
    slot arrays; empty slots carry SENTINEL keys). Scalars:
    `_count` = true global distinct, `_local_max` = max per-chip local
    distinct (sizes cap_local retries), `_overflow` = 1 if any send
    bucket or owner table overflowed (sizes cap_owner retries).
    """
    from tpu_olap.kernels.sparse_groupby import SENTINEL, merge_sparse

    D = mesh.devices.size
    B = bucket_cap(cap_local, D)
    agg_plans = plan.agg_plans

    def local(env, valid, seg_mask, consts):
        out = kernel(env, valid, seg_mask, consts)
        keys = out["_keys"]
        present = keys != SENTINEL
        owner = jnp.where(present, _owner_of(keys, D, jnp), D)

        # rank of each entry within its owner bucket: stable sort by
        # owner, then index minus a cummax of segment starts
        idx = jnp.arange(cap_local, dtype=jnp.int32)
        owner_s, order = jax.lax.sort((owner, idx), num_keys=1)
        boundary = jnp.concatenate(
            [jnp.ones((1,), bool), owner_s[1:] != owner_s[:-1]])
        seg_start = jax.lax.cummax(jnp.where(boundary, idx, 0))
        pos = jnp.zeros((cap_local,), jnp.int32) \
            .at[order].set(idx - seg_start)

        ok = present & (pos < B)
        send_overflow = (present & (pos >= B)).sum(dtype=jnp.int32)
        flat = jnp.where(ok, owner * B + jnp.minimum(pos, B - 1), D * B)

        def scatter(v, fill):
            buf = jnp.full((D * B + 1,) + v.shape[1:], fill, v.dtype)
            buf = buf.at[flat].set(v, mode="drop")
            return buf[:D * B].reshape((D, B) + v.shape[1:])

        sent = {"_keys": scatter(keys, SENTINEL)}
        for name, v in out.items():
            if name in ("_keys", "_count"):
                continue
            sent[name] = scatter(v, np.zeros((), v.dtype))

        recv = {name: jax.lax.all_to_all(v, DATA_AXIS, split_axis=0,
                                         concat_axis=0, tiled=True)
                for name, v in sent.items()}
        parts = [{k: recv[k][d] for k in recv} for d in range(D)]
        merged = merge_sparse(parts, agg_plans, cap_owner, jnp)

        owner_count = merged["_count"]
        merged["_count"] = jax.lax.psum(
            jnp.minimum(owner_count, cap_owner), DATA_AXIS)
        merged["_local_max"] = jax.lax.pmax(out["_count"], DATA_AXIS)
        merged["_overflow"] = jax.lax.pmax(
            ((owner_count > cap_owner) | (send_overflow > 0))
            .astype(jnp.int32), DATA_AXIS)
        return merged

    def specs_like(env):
        return {
            "cols": {k: P(DATA_AXIS) for k in env["cols"]},
            "nulls": {k: P(DATA_AXIS) for k in env["nulls"]},
        }

    def run(env, valid, seg_mask, consts):
        scalar = {"_count", "_local_max", "_overflow"}
        names = (["_keys", "_rows", "_count", "_local_max", "_overflow"]
                 + [p.name for p in agg_plans]
                 + [f"_nn_{p.name}" for p in agg_plans
                    if p.kind in ("min", "max")])
        f = jax.shard_map(
            local, mesh=mesh,
            in_specs=(specs_like(env), P(DATA_AXIS), P(DATA_AXIS),
                      jax.tree.map(lambda _: P(), consts)),
            out_specs={n: (P() if n in scalar else P(DATA_AXIS))
                       for n in names},
            check_vma=False,
        )
        return f(env, valid, seg_mask, consts)

    return run


def shard_put(arr: np.ndarray, mesh: Mesh):
    """Host array -> device array sharded on the leading axis.

    Uses make_array_from_callback, the multi-host-correct formulation:
    each process materializes only the shards addressable on ITS devices
    (on a single host this degenerates to a plain sharded device_put).
    With a multi-host mesh (jax.distributed initialized and make_mesh
    over global devices), every host feeds its local slice of the
    segment axis — no host ever holds the whole table (SURVEY.md §3.6:
    ICI within a slice, DCN across)."""
    sharding = NamedSharding(mesh, P(DATA_AXIS))
    return jax.make_array_from_callback(
        arr.shape, sharding, lambda idx: arr[idx])


def make_multihost_mesh(num_shards: int | None = None) -> Mesh:
    """Mesh over ALL processes' devices (call after
    jax.distributed.initialize on every host). Single-process callers
    get the same mesh make_mesh builds; multi-host callers get a 1-D
    segment axis spanning hosts — psum/all_to_all then ride ICI within a
    slice and DCN across slices, with no code change in the kernels."""
    devs = jax.devices()
    n = num_shards or len(devs)
    if n > len(devs):
        raise ValueError(f"num_shards={n} exceeds {len(devs)} devices")
    return Mesh(np.array(devs[:n]), (DATA_AXIS,))


def replicate_put(arr, mesh: Mesh):
    return jax.device_put(arr, NamedSharding(mesh, P()))


def pad_segments(n_segments: int, num_shards: int) -> int:
    """Segments must split evenly across shards; padded blocks are fully
    invalid rows (valid mask False), so results are unaffected."""
    return -(-n_segments // num_shards) * num_shards
