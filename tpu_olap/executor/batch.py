"""Shared-scan batch executor: fuse N compatible queries into ONE pass.

PROFILE_CPU.json shows a ~65 ms execute floor per query even when the
result is a single group — every query re-scans the full segment stream,
so N concurrent SSB queries cost N full scans. This module kills that
floor the way the reference's Druid broker did (many rewritten Spark
queries answered from one shared column store, SURVEY.md §3.1): queries
against the same table that lower to dense aggregation plans are fused
into one device pass in which each segment window is read once and feeds
N per-query (filter-mask, agg-plan) legs, each reusing the single-query
compile_aggregations/group_reduce machinery (kernels.groupby.
group_reduce_batch) and emitting its own independent partials dict.

Three entry points:

- run_batch(runner, queries, table): the boxed batch executor — dedupe
  identical queries (one physical scan serves every copy), fuse
  compatible dense-agg legs into one jitted program (or the chunked
  numpy shared scan on the "cpu" platform), run everything else through
  the ordinary single-query path. Per-leg failures are boxed, never
  collective.
- Coalescer: the micro-batching window. Concurrent QueryRunner.execute()
  callers enqueue; the first arrival leads, sleeps batch_window_ms, and
  dispatches everyone who arrived in the window as one batch
  (EngineConfig.batch_window_ms, off by default).
- fusable(plan, mesh): the compatibility rule, shared with tests.

Metrics: every leg of a fused dispatch records `batch_id` (count the
shared pass ONCE per id), `batch_size` (logical queries served),
`scan_ms_shared` (wall of the one shared pass) and `agg_ms` (this leg's
share of it — measured per leg on the numpy platform, attributed by
scanned-work weight on the jit platform, where the inside of one XLA
program cannot be timed per leg). See docs/BATCH_EXECUTION.md.
"""

from __future__ import annotations

import json
import threading
import time

import numpy as np

from tpu_olap.executor.runner import QueryResult, _next_pow2
from tpu_olap.ir.query import (GroupByQuerySpec, TimeseriesQuerySpec,
                               TopNQuerySpec)
from tpu_olap.kernels.groupby import group_reduce_batch, merge_partials
from tpu_olap.obs.trace import (current_query_id, span as _span,
                                use_query_id)
from tpu_olap.resilience.errors import InternalError
from tpu_olap.resilience.faults import maybe_inject

AGG_QUERY_TYPES = (TimeseriesQuerySpec, GroupByQuerySpec, TopNQuerySpec)


def fusable(plan, mesh) -> str | None:
    """None when the plan can ride a fused shared-scan dispatch, else the
    reason it must run alone (through the single-query path). Mesh legs
    fuse too: each leg's group key extends by the owning chip inside
    the ONE fused program, per-leg [D·K] partials come back sharded,
    and the host broker merges each leg (executor.sharding) — the
    shared scan happens within each chip's resident shard."""
    if plan.kind != "agg":
        return "only aggregation plans fuse"
    if plan.sparse:
        return "sparse group-by legs run alone"
    if plan.key_fn is None:
        return "plan has no batchable key_fn"
    if mesh is not None:
        if mesh.devices.size * plan.total_groups >= (1 << 31):
            return "chip-extended group key overflows int32"
        from tpu_olap.executor.sharding import is_multihost
        if is_multihost(mesh):
            return "multi-host mesh legs run alone"
    return None


def run_batch(runner, queries, table, query_ids=None) -> list:
    """Execute N queries against one table, sharing scans where possible.

    Returns a boxed list in input order: QueryResult per success,
    the exception per failed leg (the caller — Coalescer.submit or
    Engine.sql_batch — re-raises or falls back PER QUERY, preserving the
    'never an error' property query-by-query). `query_ids` (parallel to
    `queries`) carries each logical query's trace id so per-leg history
    records stay attributable across the fused dispatch; None entries
    get a fresh id at record time."""
    queries = list(queries)
    if query_ids is None:
        query_ids = [None] * len(queries)
    boxed: list = [None] * len(queries)

    # dedupe identical queries first: one physical pass serves every
    # copy (the BI dashboard-storm case — 8 users on the same panel)
    uniq: dict[str, list[int]] = {}
    for i, q in enumerate(queries):
        key = json.dumps(q.to_json(), sort_keys=True, default=str)
        uniq.setdefault(key, []).append(i)

    singles, fused = [], []   # [(query, duplicate indexes, plan)]
    for idxs in uniq.values():
        q = queries[idxs[0]]
        # batch legs consult the same full-result tier as single-query
        # dispatch: a cached leg is served (and fanned out to its
        # duplicates) without lowering, fusing, or touching the device
        with use_query_id(query_ids[idxs[0]] or None):
            cached = runner._serve_full_cache(q, table)
        if cached is not None:
            _fan_out(runner, boxed, cached, idxs, queries, query_ids)
            continue
        try:
            plan = runner._lower_cached(q, table)
            reason = fusable(plan, runner.mesh) \
                if isinstance(q, AGG_QUERY_TYPES) else "non-agg query type"
        except Exception as e:  # noqa: BLE001 — boxed per leg
            for i in idxs:
                boxed[i] = e
            continue
        (fused if reason is None else singles).append((q, idxs, plan))

    # window compatibility (the ISSUE's "same segment window" rule):
    # every leg of a fused pass computes over the UNION window, so legs
    # with disjoint pruned windows would multiply per-leg scan work
    # instead of amortizing it — fuse only overlap clusters
    clusters, alone = _window_clusters(fused)
    singles.extend(alone)
    fused_groups = []
    for cl in clusters:
        if len(cl) == 1:
            # a lone fusable leg gains nothing from the fused program:
            # run it on the richer single-query path (packed fetch,
            # per-plan window) — the dedupe above is still a shared
            # scan when it serves several copies
            singles.append(cl[0])
        else:
            fused_groups.append(cl)

    for q, idxs, plan in singles:
        try:
            # _execute_guarded, not _execute: the single-leg path keeps
            # the deadline watchdog + wedged-device reprobe of a plain
            # execute() call (serialized mode: run_batch's caller holds
            # dispatch_lock; pipelined mode: the leg's own enqueue
            # sections take it). The statement's own id is propagated
            # BEFORE record() fires, so the history record and its
            # `query` event agree (a post-hoc rewrite would leave the
            # event carrying the leader's trace id).
            with use_query_id(query_ids[idxs[0]] or None):
                res = runner._execute_guarded(q, table)
        except BaseException as e:  # noqa: BLE001 — boxed per leg
            for i in idxs:
                boxed[i] = e
            continue
        if len(idxs) > 1:
            m = res.metrics
            m["batch_id"] = runner._next_batch_id()
            m["batch_size"] = len(idxs)
            m["batch_legs"] = 1
            m["scan_ms_shared"] = m.get("execute_ms", 0.0)
            m["agg_ms"] = m.get("execute_ms", 0.0)
            runner._m_batch.observe(len(idxs))
        _fan_out(runner, boxed, res, idxs, queries, query_ids)

    maxq = max(2, int(runner.config.batch_max_queries))
    for cl in fused_groups:
        # canonical leg order => one fused program per batch COMPOSITION
        # (the jit cache is keyed on the ordered fingerprint tuple)
        cl.sort(key=lambda t: repr(t[2].fingerprint()))
        for k in range(0, len(cl), maxq):
            group = cl[k:k + maxq]
            try:
                if len(group) == 1:  # a max-size split remainder
                    q, idxs, plan = group[0]
                    results = [runner._execute_guarded(q, table)]
                else:
                    results = _run_fused(runner, table, group, query_ids)
            except BaseException as e:  # noqa: BLE001 — boxed per leg
                for _, idxs, _ in group:
                    for i in idxs:
                        boxed[i] = e
                continue
            for (q, idxs, _), res in zip(group, results):
                if query_ids[idxs[0]]:
                    res.metrics["query_id"] = query_ids[idxs[0]]
                _fan_out(runner, boxed, res, idxs, queries, query_ids)
    return boxed


def _window_clusters(fused):
    """Partition fusable legs into overlap clusters: a leg joins a
    cluster only while one union-window pass over the cluster costs no
    more than ~1.3x the legs' individual windowed passes (each fused
    leg computes over the whole union window — pruned-away segments
    multiply by zero but still cost compute). Legs with no pruned
    segments (empty intervals) come back in the second list and take
    the single-query path. Greedy over span-sorted legs, so clustering
    is deterministic and repeated workloads hit the same fused-program
    compositions in the jit cache."""
    spans, alone = [], []
    for item in fused:
        plan = item[2]
        ids = plan.pruned_ids if not plan.empty else []
        if not ids:
            alone.append(item)
            continue
        spans.append((min(ids), max(ids) + 1, item))
    spans.sort(key=lambda s: (s[0], s[1]))
    clusters = []
    cur, cur_lo, cur_hi, cur_sum = [], 0, 0, 0
    for lo, hi, item in spans:
        if cur:
            u_lo, u_hi = min(cur_lo, lo), max(cur_hi, hi)
            if (len(cur) + 1) * (u_hi - u_lo) \
                    <= 1.3 * (cur_sum + hi - lo):
                cur.append(item)
                cur_lo, cur_hi = u_lo, u_hi
                cur_sum += hi - lo
                continue
            clusters.append(cur)
        cur, cur_lo, cur_hi, cur_sum = [item], lo, hi, hi - lo
    if cur:
        clusters.append(cur)
    return clusters, alone


def _fan_out(runner, boxed, res, idxs, queries, query_ids=None):
    """First duplicate gets the computed result; the rest share its rows
    (the scan ran once) under their own QueryResult + history record
    carrying its own query_id."""
    boxed[idxs[0]] = res
    for i in idxs[1:]:
        m = {**res.metrics, "batch_dedup": True}
        # a duplicate is its own logical query: never inherit the
        # computing leg's id (record() would otherwise stamp the batch
        # leader's trace id on every fan-out copy) — nor its compile
        # attribution (one executable build must not re-increment
        # compile_ms_total once per duplicate)
        m.pop("recompiles", None)
        m.pop("compile_ms", None)
        m["query_id"] = (query_ids[i] if query_ids and query_ids[i]
                         else runner.tracer.new_query_id())
        dup = QueryResult(queries[i], res.rows, res.druid, m)
        runner.record(dup.metrics)
        boxed[i] = dup


# ------------------------------------------------------------- fused pass


def _run_fused(runner, table, group, query_ids=None):
    """group: >= 2 unique dense-agg legs against one table. Build the
    union env ONCE, run ONE fused pass, finalize/assemble per leg.
    When a trace is active (the leader's — followers' traces show only
    their coalesce wait), the fused pass appears as one `shared-scan`
    span with every logical leg nested under it."""
    from tpu_olap.executor.results import (agg_specs_by_name, eval_post_aggs,
                                           finalize_aggs, theta_raw_fields)

    t_start = time.perf_counter()
    plans = [p for _, _, p in group]
    n_logical = sum(len(idxs) for _, idxs, _ in group)
    batch_id = runner._next_batch_id()
    runner._m_batch.observe(n_logical)
    # per-leg workload fingerprints (obs.workload): fused legs are real
    # logical queries and must attribute to their own templates — the
    # `_wl` key is consumed by record(), so keep a parallel list for
    # the full-cache store below
    leg_fps = [runner.fingerprint(q, table.name) for q, _, _ in group]
    metrics_list = [{"query_type": q.query_type, "datasource": table.name,
                     "batch_id": batch_id, "batch_size": n_logical,
                     "batch_legs": len(group), "_wl": fp}
                    for (q, _, _), fp in zip(group, leg_fps)]
    if query_ids is not None:
        for (_, idxs, _), m in zip(group, metrics_list):
            if query_ids[idxs[0]]:
                m["query_id"] = query_ids[idxs[0]]

    def dispatch():
        # env build lives INSIDE the retried callable: a _dispatch retry
        # purges the table's device state, so the rebuilt attempt must
        # re-prepare (stale buffers could be poisoned by a device reset).
        # Two-staged like the single-query path (ISSUE 10): stage 1
        # (env build + fused program fire) under the enqueue lock,
        # stage 2 (transfer / the numpy shared scan) lock-free — the
        # leader no longer holds dispatch_lock while it computes or
        # assembles.
        with runner._pipeline_slot():
            with runner._enqueue_lock(metrics_list[0]):
                leg_envs, seg_masks = [], []
                valid = None
                for plan, m in zip(plans, metrics_list):
                    env, valid, seg_mask = runner._prepare(plan, m)
                    leg_envs.append(env)
                    seg_masks.append(seg_mask)
                win = _union_window(plans, len(seg_masks[0]),
                                    runner.mesh)
                if win is not None:
                    # same units as the single-query mesh path:
                    # segments_window is the GLOBAL window (W x D under
                    # a mesh), per_chip the local width
                    D_win = runner.mesh.devices.size \
                        if runner.mesh is not None else 1
                    for m in metrics_list:
                        m["segments_window"] = win[1] * D_win
                        if runner.mesh is not None:
                            m["segments_window_per_chip"] = win[1]
                enq = pin = None
                if runner.config.platform != "cpu":
                    enq = _enqueue_fused_device(
                        runner, table, plans, leg_envs, valid,
                        seg_masks, win)
                    pin = runner._pin_inflight(enq[0])
            if metrics_list[0].get("pipelined"):
                for m in metrics_list[1:]:
                    m["pipelined"] = True
            if enq is None:
                # numpy shared scan: the chunked compute reads only its
                # own env references, so it runs outside the lock
                return _run_fused_numpy(runner, plans, leg_envs, valid,
                                        seg_masks, win) + (False,)
            outs_dev, hit, t_fire = enq
            outs = runner._fetch_tree(outs_dev, metrics_list[0], pin)
            if runner.mesh is not None:
                # broker step: each leg's per-chip [D·K] unfinalized
                # partials fold on the host with the segment-cache
                # merge algebra (executor.sharding.broker_merge)
                from tpu_olap.executor.sharding import broker_merge
                D = runner.mesh.devices.size
                outs = [broker_merge(o, p.agg_plans, D)
                        for o, p in zip(outs, plans)]
            shared_ms = (time.perf_counter() - t_fire) * 1000
            # per-leg attribution: one XLA program cannot be timed from
            # outside per leg; split the shared wall by each leg's
            # scanned-work weight (columns read x segments scanned x
            # agg plans) — an estimate, labeled as such in
            # docs/BATCH_EXECUTION.md
            w = [max(1, (len(p.columns) + 1) * max(1, len(p.pruned_ids))
                     * (len(p.agg_plans) + 1)) for p in plans]
            tw = float(sum(w))
            agg_ms = [shared_ms * wi / tw for wi in w]
            return outs, shared_ms, agg_ms, hit

    # retry-based recovery identical to the single-query path (the
    # shared metrics of leg 0 carry any retry_errors), under the same
    # deadline/wedge guard — a wedged device must not hang every
    # coalesced caller past query_deadline_s
    with _span("shared-scan", batch_id=batch_id, batch_legs=len(group),
               batch_size=n_logical) as ssp:
        partials_list, shared_ms, agg_ms, hit = runner._guarded_dispatch(
            dispatch, metrics_list[0], table.name)
        if not hit and runner.config.platform != "cpu":
            # one fused executable per batch composition: attribute the
            # build to the first leg's record (counting it on every leg
            # would multiply one compile by batch_legs in /metrics)
            runner._note_compile("batch", metrics_list[0])
        ssp.set(jit_cache_hit=hit, scan_ms_shared=round(shared_ms, 3))

        results = []
        for leg_i, ((q, idxs, plan), m, partials, leg_ms) in enumerate(
                zip(group, metrics_list, partials_list, agg_ms)):
            t0 = time.perf_counter()
            with ssp.span("leg") as lsp:
                # per-batch-leg fault site (resilience.faults): a leg
                # failure here boxes the whole group, and every logical
                # caller falls back per query — testable without a
                # device fault mid-XLA-program
                maybe_inject(runner.config, "batch-leg", leg_i)
                specs = agg_specs_by_name(q.aggregations)
                keep_raw = theta_raw_fields(q.post_aggregations)
                arrays = finalize_aggs(partials, plan.agg_plans, specs,
                                       keep_raw)
                eval_post_aggs(arrays, q.post_aggregations)
                res = runner._assemble_agg(q, plan, arrays)
            m["scan_ms_shared"] = shared_ms
            m["agg_ms"] = leg_ms
            m["jit_cache_hit"] = hit
            m["num_shards"] = runner.mesh.devices.size \
                if runner.mesh is not None else 1
            m["assemble_ms"] = (time.perf_counter() - t0) * 1000
            m["total_ms"] = (time.perf_counter() - t_start) * 1000
            res.metrics = m
            runner.record(m)
            # fused legs populate the same full-result tier the
            # single-query path serves from (docs/CACHING.md)
            runner._store_full_cache(q, table, res, leg_fps[leg_i])
            lsp.set(query_id=m["query_id"], query_type=m["query_type"],
                    agg_ms=round(leg_ms, 3), duplicates=len(idxs))
            results.append(res)
    return results


def _union_window(plans, n_segments, mesh=None):
    """(lo, W) covering every leg's pruned segments, or None — the batch
    analog of QueryRunner._segment_window. Legs whose own pruned set is
    smaller still read only the union window; their per-leg seg_mask
    zeroes the rest (adding exact zeros, so per-query results stay
    bitwise identical to the single-query windowed pass). Under a mesh
    the window is the per-chip LOCAL one (interleaved placement:
    logical [lo, hi) is local [lo//D, ceil(hi/D)) on every chip)."""
    ids = sorted({i for p in plans if not p.empty for i in p.pruned_ids})
    if not ids:
        return None
    if mesh is not None:
        from tpu_olap.executor.sharding import local_window
        D = mesh.devices.size
        return local_window(ids, D, n_segments // D)
    lo, hi = ids[0], ids[-1] + 1
    W = _next_pow2(hi - lo)
    if 4 * W >= 3 * n_segments:
        return None
    return min(lo, n_segments - W), W


def _buffer_layout(leg_envs):
    """Unique env arrays -> one flat buffer list + per-leg {name: index}
    specs. Buffers shared across legs (same ds column) appear ONCE —
    that is the 'read each column once' half of the shared scan. The
    layout is deterministic given the legs' column sets, so a cached
    fused program (keyed on the ordered fingerprint tuple) always sees
    buffers in the order its closure captured."""
    buffers, index, layouts = [], {}, []
    for env in leg_envs:
        spec = {"cols": {}, "nulls": {}}
        for kind in ("cols", "nulls"):
            for name, arr in env[kind].items():
                j = index.get(id(arr))
                if j is None:
                    j = index[id(arr)] = len(buffers)
                    buffers.append(arr)
                spec[kind][name] = j
        layouts.append(spec)
    return buffers, layouts


def _layout_key(layouts):
    """Hashable form of per-leg buffer layouts for the jit-cache key."""
    return tuple((tuple(sorted(s["cols"].items())),
                  tuple(sorted(s["nulls"].items()))) for s in layouts)


def _build_fused(plans, layouts, mesh_dims=None):
    """The fused kernel: every leg's (filter, dims, key) front half runs
    over the shared buffers, then kernels.groupby.group_reduce_batch
    emits N independent partials dicts — all traced into one program.
    mesh_dims=(D, blocks): each leg's key extends by the owning chip
    (row block b belongs to chip b // blocks in placement order), so
    per-leg [D·K] partials come back sharded and the host broker
    merges them (executor.sharding.broker_merge)."""
    def fused(buffers, valid, seg_masks, consts_list):
        legs = []
        for plan, spec, sm, consts in zip(plans, layouts, seg_masks,
                                          consts_list):
            env = {"cols": {n: buffers[j]
                            for n, j in spec["cols"].items()},
                   "nulls": {n: buffers[j]
                             for n, j in spec["nulls"].items()}}
            fenv, mask, key = plan.key_fn(env, valid, sm, consts)
            num_groups = plan.total_groups
            if mesh_dims is not None:
                from tpu_olap.executor.sharding import chip_extended_key
                D, blocks = mesh_dims
                key = chip_extended_key(key, mask, D, blocks,
                                        num_groups)
                num_groups = D * num_groups
            legs.append((key, mask, fenv, plan.agg_plans, num_groups))
        return group_reduce_batch(legs, consts_list)
    return fused


def _window_fused(fused, W: int, mesh=None, per_chip: int = 0):
    """Dynamic-slice every [S, ...] input to the union window before the
    fused compute (one compile per (composition, W); `lo` is traced).
    Under a mesh the slice is per-chip LOCAL (reshape to (chip, local),
    slice the unsharded local axis — no cross-chip movement)."""
    import jax

    if mesh is not None:
        from tpu_olap.executor.sharding import _slice_local
        D = mesh.devices.size

        def fn(buffers, valid, seg_masks, consts_list, lo):
            def sl(a):
                return _slice_local(a, D, per_chip, lo, W)
            return fused([sl(b) for b in buffers], sl(valid),
                         [sl(m) for m in seg_masks], consts_list)
        return fn

    def fn(buffers, valid, seg_masks, consts_list, lo):
        def sl(a):
            return jax.lax.dynamic_slice_in_dim(a, lo, W, axis=0)
        return fused([sl(b) for b in buffers], sl(valid),
                     [sl(m) for m in seg_masks], consts_list)
    return fn


def _enqueue_fused_device(runner, table, plans, leg_envs, valid,
                          seg_masks, win):
    """Stage 1 of the fused pass (caller holds the enqueue lock): one
    jitted fused program per batch composition, fired asynchronously.
    Returns (device output trees, jit-cache hit, fire timestamp); the
    caller transfers with runner._fetch_tree outside the lock."""
    import jax

    buffers, layouts = _buffer_layout(leg_envs)
    mesh = runner.mesh
    D = mesh.devices.size if mesh is not None else 0
    per_chip = len(seg_masks[0]) // D if mesh is not None else 0
    # the layout is part of the key: a cached program's closure bakes in
    # its compile-time {name: buffer-index} maps, and the SHARING
    # structure can legitimately change between dispatches (an HBM-ledger
    # eviction between two legs' _prepare calls refetches a column as a
    # distinct object) — reusing the old closure over a differently-
    # shaped buffer list would read the wrong column
    key = (table.name, "batch", D,
           tuple(p.fingerprint() for p in plans),
           win[1] if win else 0,
           _layout_key(layouts))
    jitted = runner._jit_cache.get(key)
    hit = jitted is not None
    if not hit:
        mesh_dims = None
        if mesh is not None:
            mesh_dims = (D, win[1] if win is not None else per_chip)
        fused = _build_fused(plans, layouts, mesh_dims)
        if win is not None:
            fused = _window_fused(fused, win[1], mesh, per_chip)
        if mesh is not None:
            from tpu_olap.executor.sharding import shard_spec
            jitted = jax.jit(fused, out_shardings=shard_spec(mesh))
        else:
            jitted = jax.jit(fused)
        runner._jit_cache[key] = jitted
    consts_list, seg_args = [], []
    for plan, sm in zip(plans, seg_masks):
        cdev, sarg = runner._args_for(plan, sm, mesh)
        consts_list.append(cdev)
        seg_args.append(sarg)
    t0 = time.perf_counter()
    outs = jitted(buffers, valid, seg_args, consts_list, win[0]) \
        if win is not None else jitted(buffers, valid, seg_args,
                                       consts_list)
    if mesh is not None:
        runner._note_chip_dispatch(range(D))
    return outs, hit, t0


def _run_fused_numpy(runner, plans, leg_envs, valid, seg_masks, win):
    """Chunked shared scan on the numpy platform: the union segment
    window is sliced chunk by chunk, and every leg's kernel runs over
    the chunk while it is cache-hot — each chunk's bytes stream from
    DRAM once for all N legs instead of once per query. Chunks fan out
    over a small thread pool (numpy releases the GIL on large array
    ops). Per-leg partials merge in chunk order via merge_partials;
    note chunked float sums can differ from the single-pass path in the
    last ulp (addition reorders across chunk boundaries)."""
    valid = np.asarray(valid)
    n_seg = len(seg_masks[0])
    lo, hi = (win[0], win[0] + win[1]) if win is not None else (0, n_seg)
    C = max(1, int(runner.config.batch_chunk_segments))
    bounds = [(a, min(a + C, hi)) for a in range(lo, hi, C)]
    t_all = time.perf_counter()
    agg_ms = [0.0] * len(plans)
    mu = threading.Lock()

    def slice_env(env, sl):
        return {"cols": {n: v[sl] for n, v in env["cols"].items()},
                "nulls": {n: v[sl] for n, v in env["nulls"].items()}}

    def one_chunk(b):
        a, z = b
        sl = slice(a, z)
        outs = []
        for i, plan in enumerate(plans):
            sm = seg_masks[i][sl]
            if not sm.any():
                outs.append(None)
                continue
            t0 = time.perf_counter()
            out = plan.kernel(slice_env(leg_envs[i], sl), valid[sl], sm,
                              plan.pool.consts)
            dt = (time.perf_counter() - t0) * 1000
            with mu:
                agg_ms[i] += dt
            outs.append({k: np.asarray(v) for k, v in out.items()})
        return outs

    threads = int(runner.config.batch_cpu_threads)
    if threads == 0:
        import os
        threads = min(4, os.cpu_count() or 1)
    if threads > 1 and len(bounds) > 1:
        from concurrent.futures import ThreadPoolExecutor
        with ThreadPoolExecutor(max_workers=threads) as ex:
            chunk_outs = list(ex.map(one_chunk, bounds))
    else:
        chunk_outs = [one_chunk(b) for b in bounds]

    partials_list = []
    for i, plan in enumerate(plans):
        acc = None
        for outs in chunk_outs:
            o = outs[i]
            if o is None:
                continue
            acc = o if acc is None else merge_partials(acc, o,
                                                       plan.agg_plans)
        if acc is None:
            # fully pruned/empty leg: one all-masked evaluation over a
            # single segment yields the correctly-shaped zero partials
            a = min(lo, max(0, n_seg - 1))
            z = min(a + 1, n_seg)
            sl = slice(a, z)
            acc = plan.kernel(slice_env(leg_envs[i], sl), valid[sl],
                              np.zeros(z - a, bool), plan.pool.consts)
            acc = {k: np.asarray(v) for k, v in acc.items()}
        partials_list.append(acc)
    shared_ms = (time.perf_counter() - t_all) * 1000
    # with chunks fanned over threads, per-leg CPU times sum past the
    # shared wall; rescale so sum(agg_ms) <= scan_ms_shared holds (the
    # documented attribution invariant) while keeping relative weights
    total = sum(agg_ms)
    if total > shared_ms > 0:
        agg_ms = [a * shared_ms / total for a in agg_ms]
    return partials_list, shared_ms, agg_ms


# -------------------------------------------------------------- coalescer


class _Pending:
    __slots__ = ("query", "table", "event", "result", "error", "qid")

    def __init__(self, query, table):
        self.query = query
        self.table = table
        self.event = threading.Event()
        self.result = None
        self.error = None
        # capture the submitting caller's trace id: the leader executes
        # every follower's query on its own thread, so the fused legs'
        # history records must be re-attributed at record time
        self.qid = current_query_id()


class Coalescer:
    """Micro-batching window: the first concurrent caller leads, waits
    batch_window_ms for companions, and dispatches everyone who arrived
    as ONE run_batch call under the runner's dispatch lock. Followers
    block on an event; per-query failures propagate to their own caller
    only. A caller arriving after a leader has cut its batch becomes the
    next leader, so windows pipeline under sustained load."""

    def __init__(self, runner, window_s: float):
        self.runner = runner
        self.window_s = window_s
        self._mu = threading.Lock()
        self._queue: list = []
        self._collecting = False

    def submit(self, query, table):
        me = _Pending(query, table)
        with self._mu:
            self._queue.append(me)
            lead = not self._collecting
            if lead:
                self._collecting = True
        if not lead:
            me.event.wait()
            if me.error is not None:
                raise me.error
            return me.result
        # everything from here runs under try/finally: an async
        # exception in the leader (KeyboardInterrupt mid-sleep) must
        # still reset _collecting, drain the queue, and wake every
        # follower — else the coalescer is wedged for the process life
        batch: list = []
        try:
            try:
                if self.window_s > 0:
                    time.sleep(self.window_s)
            finally:
                with self._mu:
                    batch, self._queue = self._queue, []
                    self._collecting = False
            by_table: dict = {}
            for it in batch:
                by_table.setdefault(id(it.table), []).append(it)
            for items in by_table.values():
                try:
                    # _execute_batch_boxed = admission slot (ONE per
                    # fused submission, shed -> every caller gets the
                    # QueryShed) + dispatch_lock + run_batch
                    boxed = self.runner._execute_batch_boxed(
                        [it.query for it in items], items[0].table,
                        [it.qid for it in items])
                except BaseException as e:  # noqa: BLE001 — fan out
                    boxed = [e] * len(items)
                for it, b in zip(items, boxed):
                    if isinstance(b, BaseException):
                        it.error = b
                    else:
                        it.result = b
        finally:
            for it in batch:
                if it.result is None and it.error is None:
                    it.error = InternalError(
                        "batch leader exited without a result")
                it.event.set()
        if me.error is not None:
            raise me.error
        return me.result
