"""Query lowering: QuerySpec + TableSegments -> PhysicalPlan.

The analog of DruidStrategy's physical planning + Druid's per-query engine
setup (SURVEY.md §4.2), redesigned for XLA's trace-once model: the lowered
kernel closure only reads literals from a named ConstPool dict, so one
jitted program serves every query sharing the same *template* (same spec
structure, different literals) — the compile-cache requirement that makes
sub-500ms p50 possible (SURVEY.md §8.4 #3). Anything the dense device path
can't express raises Unsupported*, which the planner treats as "not
rewritable" -> fallback (SURVEY.md §2 property 2).
"""

from __future__ import annotations

import os.path
from dataclasses import dataclass, field

import numpy as np

from tpu_olap.ir.interval import ETERNITY
from tpu_olap.ir.query import (GroupByQuerySpec, ScanQuerySpec,
                               SearchQuerySpec, SelectQuerySpec,
                               TimeseriesQuerySpec, TopNQuerySpec)
from tpu_olap.kernels.exprs import materialize_virtuals
from tpu_olap.kernels.filtereval import ConstPool, compile_filter
from tpu_olap.kernels.groupby import (UnsupportedAggregation,
                                      build_group_key, compile_aggregations,
                                      group_reduce)
from tpu_olap.kernels.timebucket import compile_granularity
from tpu_olap.executor.dimplan import compile_dimension
from tpu_olap.segments.segment import ColumnType, TIME_COLUMN


@dataclass
class PhysicalPlan:
    query: object
    table: object
    kind: str                  # "agg" | "mask" (scan/select)
    pool: ConstPool = None
    kernel: object = None      # unjitted fn(env, valid, segmask, consts)
    statics: tuple = ()        # part of the compile-cache key
    dim_plans: list = field(default_factory=list)
    bucket_plan: object = None
    agg_plans: list = field(default_factory=list)
    sizes: tuple = ()          # (n_buckets, dim sizes...) radix order
    total_groups: int = 1
    pruned_ids: list = field(default_factory=list)
    t_min: int = 0
    t_max: int = 0
    empty: bool = False        # intervals don't touch the table at all
    columns: tuple = ()        # physical columns the kernel reads
    null_cols: tuple = ()
    virtual_exprs: dict = field(default_factory=dict)
    # (token, source_col, const_name) derived streams the compiled
    # filters need (columnComparison code translation); the runner
    # materializes each once per content token (see dataset.derived)
    filter_streams: tuple = ()
    pallas_reason: str | None = "not attempted"  # None = pallas kernel active
    sparse: bool = False       # sort-based path for huge group spaces
    make_sparse_kernel: object = None   # cap -> kernel fn (sparse only)
    # fn(env, valid, seg_mask, consts) -> (fenv, mask, key): the plan's
    # filter+dim front half WITHOUT the reduce, so the batch executor
    # can fuse N legs' reduces over one shared scan (dense agg only;
    # always the generic jnp/numpy path even when plan.kernel is Pallas)
    key_fn: object = None

    def fingerprint(self) -> tuple:
        # memoized: plans are immutable once lowered and (round 3) cached
        # across executions, so the template serialization — a couple ms
        # of json for wide queries — is paid once, not per dispatch
        fp = getattr(self, "_fp", None)
        if fp is None:
            import json
            t = _template(self.query.to_json())
            fp = self._fp = (
                self.table.name, json.dumps(t, sort_keys=True),
                self.statics,
                self.pool.signature() if self.pool is not None else ())
        return fp


_LITERAL_KEYS = {"value", "values", "lower", "upper", "pattern", "intervals"}


def _template(j):
    """Strip literal values from a query-JSON tree, keep structure.

    Expression subtrees (virtual columns, expression filters) are kept
    VERBATIM including their literals: those literals are traced into the
    jitted program as XLA constants (they never ride the ConstPool), so
    stripping them would alias distinct programs in the compile cache —
    `sum(x*2)` vs `sum(x*3)` must not share a fingerprint.
    """
    if isinstance(j, dict):
        if j.get("type") == "expression":
            return j
        return {k: ("?" if k in _LITERAL_KEYS else _template(v))
                for k, v in j.items()}
    if isinstance(j, list):
        return [_template(x) for x in j]
    return j


def lower(query, table, config) -> PhysicalPlan:
    if isinstance(query, (TimeseriesQuerySpec, GroupByQuerySpec,
                          TopNQuerySpec)):
        return _lower_agg(query, table, config)
    if isinstance(query, (ScanQuerySpec, SelectQuerySpec)):
        return _lower_mask(query, table, config)
    if isinstance(query, SearchQuerySpec):
        raise AssertionError("search queries lower via runner._run_search")
    raise UnsupportedAggregation(
        f"no device lowering for {type(query).__name__}")


def _sparse_reject_reason(query, total, config) -> str | None:
    """None when the sort-based sparse path can serve this shape, else
    why not — the single source of truth for both the over-budget
    routing decision and the in-branch rejections (GroupBy only: the
    timeseries/topN assemblers index the dense bucket space)."""
    if not isinstance(query, GroupByQuerySpec):
        return f"{query.query_type} has no sparse path"
    if total >= (1 << 62):
        return "the group space overflows the int64 sparse key"
    if not config.enable_x64:
        return "sparse group-by needs int64 keys (enable_x64=False)"
    return None


def _mesh_size(config) -> int:
    """Devices the runner will shard over. QueryRunner builds a mesh
    ONLY when num_shards > 1 is explicitly configured (runner.mesh);
    unsharded runs must not have their sketch state budgeted at
    device-count multiples they never allocate."""
    return int(config.num_shards) if config.num_shards else 1


def _radix(p) -> int:
    """Per-group state width of an aggregation plan: HLL register file,
    theta value table, or 1 for scalar accumulators. Shared by the
    sketch-state budget and the no-x64 int32 index guard."""
    from tpu_olap.kernels.hll import NUM_REGISTERS
    if p.kind == "hll":
        return NUM_REGISTERS
    return p.theta_k if p.kind == "theta" else 1


def _time_range(query, table):
    intervals = query.intervals or (ETERNITY,)
    t0, t1 = table.time_boundary
    lo = max(t0, min(iv.start for iv in intervals))
    hi = min(t1, max(iv.end for iv in intervals) - 1)
    return intervals, lo, hi, hi < lo


def _interval_mask_fn(intervals, t0, t1, pool):
    """None if intervals cover the whole table; else fn(env,c)->mask."""
    covered = any(iv.start <= t0 and iv.end > t1 for iv in intervals)
    if covered:
        return None
    starts = pool.add(np.asarray([iv.start for iv in intervals], np.int64))
    ends = pool.add(np.asarray([iv.end for iv in intervals], np.int64))

    def fn(env, c):
        t = env["cols"][TIME_COLUMN]
        return ((t[..., None] >= c[starts]) & (t[..., None] < c[ends])
                ).any(axis=-1)
    return fn


def _filter_numeric_bounds(spec, table, vexprs=None) -> dict:
    """Per-column [lo, hi] requirements implied by top-level AND
    conjuncts of the filter, for manifest pruning (SURVEY.md §3.5 P4's
    numeric-bounds leg — the denormalized-dim analog of interval
    pruning: with time-partitioned ingest a selector like d_year = 1993
    sees tight per-segment min/max and drops whole partitions before
    dispatch). Conservative: plain LONG columns only, no extraction fns,
    numeric-ordered bounds; OR/NOT shapes contribute nothing; strict
    bounds prune with their inclusive envelope (a superset scan is
    always correct — the kernel's filter stays exact)."""
    from tpu_olap.ir.filters import (AndFilter, BoundFilter, InFilter,
                                     SelectorFilter)

    def _num(v):
        try:
            return int(v)
        except (TypeError, ValueError):
            try:
                return float(v)
            except (TypeError, ValueError):
                return None

    out: dict = {}

    def add(col, lo, hi):
        # a virtual column shadows any same-named physical column in
        # filter evaluation — its values are an expression, so the
        # physical manifest's min/max say nothing about it
        if vexprs and col in vexprs:
            return
        if table.schema.get(col) is not ColumnType.LONG:
            return
        plo, phi = out.get(col, (None, None))
        if lo is not None:
            plo = lo if plo is None else max(plo, lo)
        if hi is not None:
            phi = hi if phi is None else min(phi, hi)
        out[col] = (plo, phi)

    def walk(f):
        if isinstance(f, AndFilter):
            for g in f.fields:
                walk(g)
        elif isinstance(f, SelectorFilter) and f.extraction_fn is None:
            v = _num(f.value)
            if v is not None:
                add(f.dimension, v, v)
        elif isinstance(f, InFilter) \
                and getattr(f, "extraction_fn", None) is None:
            vs = [_num(v) for v in f.values]
            if vs and all(v is not None for v in vs):
                add(f.dimension, min(vs), max(vs))
        elif isinstance(f, BoundFilter) and f.extraction_fn is None \
                and f.ordering == "numeric":
            add(f.dimension, _num(f.lower), _num(f.upper))

    if spec is not None:
        walk(spec)
    return out


def _elide_covered_imask(imask_fn, pruned_segs, intervals):
    """Residual interval-mask elision (SURVEY.md §3.5 P4 extended to row
    level): ingest globally time-sorts rows, so a scanned segment's
    [time_min, time_max] usually sits entirely inside one query interval
    — the row-level mask is then constant-true over every scanned block,
    and the kernel neither evaluates it nor reads __time for it (8
    bytes/row of HBM scan traffic on a v5e, typically the single widest
    column a filtered aggregate touches). Segments straddling an
    interval edge keep the device mask. Compile-time decision: pruning
    is static per plan, so the elision caches with the template."""
    if imask_fn is None or not pruned_segs:
        return imask_fn
    if all(any(iv.start <= s.meta.time_min and iv.end > s.meta.time_max
               for iv in intervals) for s in pruned_segs):
        return None
    return imask_fn


def _collect_columns(table, query, dim_plans, agg_plans, vexprs,
                     need_time: bool):
    cols: set[str] = set()
    if query.filter is not None:
        cols |= query.filter.columns()
    for p in agg_plans:
        cols |= set(p.fields)
    for dp in dim_plans:
        if dp.source_col:
            cols.add(dp.source_col)
    # expand virtual column references to their physical inputs
    phys: set[str] = set()
    for c in cols:
        if c in vexprs:
            phys |= vexprs[c].columns()
        else:
            phys.add(c)
    # filters on agg-inside filters already included via p.fields? filtered
    # agg filters reference columns through compile-time closures; collect
    for a in query.aggregations if hasattr(query, "aggregations") else ():
        from tpu_olap.ir.aggregations import FilteredAggregation
        if isinstance(a, FilteredAggregation):
            for c in a.filter.columns():
                phys |= vexprs[c].columns() if c in vexprs else {c}
    if need_time:
        phys.add(TIME_COLUMN)
    unknown = [c for c in phys if c not in table.schema]
    if unknown:
        from tpu_olap.kernels.filtereval import UnsupportedFilter
        raise UnsupportedFilter(f"unknown columns {unknown}")
    null_cols = tuple(sorted(
        c for c in phys if table.schema[c] is not ColumnType.STRING))
    return tuple(sorted(phys)), null_cols


def _filter_value_sets(filter_spec) -> dict:
    """Literal restrictions implied by top-level AND conjuncts:
    {column: allowed value set}. Plain selector / IN / OR-of-selectors
    only (no extraction fns) — the shapes whose passing rows provably
    carry one of the listed values in that column."""
    from tpu_olap.ir import filters as F
    conjs = list(filter_spec.fields) \
        if isinstance(filter_spec, F.AndFilter) else [filter_spec]
    out: dict = {}
    for c in conjs:
        col = vs = None
        if isinstance(c, F.SelectorFilter) and c.extraction_fn is None \
                and c.value is not None:
            col, vs = c.dimension, {c.value}
        elif isinstance(c, F.InFilter) and c.extraction_fn is None:
            # extraction-IN values are post-extraction strings, NOT raw
            # column values — they must not restrict the dim domain
            col = c.dimension
            vs = {v for v in c.values if v is not None}
        elif isinstance(c, F.OrFilter):
            cols, vals, ok = set(), set(), True
            for f in c.fields:
                if isinstance(f, F.SelectorFilter) \
                        and f.extraction_fn is None \
                        and f.value is not None:
                    cols.add(f.dimension)
                    vals.add(f.value)
                else:
                    ok = False
                    break
            if ok and len(cols) == 1:
                col, vs = next(iter(cols)), vals
        if col is not None:
            out[col] = vs if col not in out else (out[col] & vs)
    return out


def _restrict_dims(dim_plans, filter_spec, table, pool):
    """Shrink grouped string dims whose domain a filter restricts to a
    literal set: the dense id space drops from |dictionary| to |set|+1
    via a code remap (rows outside the set are masked by the same filter
    anyway, so they may map to the null slot). Two restriction sources:

    - direct: the filter names the grouped column itself (Q3.3/Q3.4's
      city IN (...) — 113k-slot tables drop to single digits);
    - FD hop: the filter names a column the grouped one determines
      (declared star FD, SURVEY.md §3.4), e.g. s_nation='US' restricting
      grouped s_city to the cities observed with that nation — verified
      against the data (fd_code_map), never trusted blindly.
    """
    if filter_spec is None:
        return dim_plans
    sets = _filter_value_sets(filter_spec)
    if not sets:
        return dim_plans
    from tpu_olap.executor.dimplan import DimPlan
    fds = table.star.functional_dependencies if table.star else ()
    out = []
    for dp in dim_plans:
        if dp.kind != "codes":
            out.append(dp)
            continue
        d = table.dictionaries[dp.source_col]
        allowed = None  # None = unrestricted; else set of codes (> 0)

        vs = sets.get(dp.source_col)
        if vs is not None:
            allowed = {c for v in vs if (c := d.id_of(v)) > 0}
        for fd in fds:
            if fd.determinant != dp.source_col:
                continue
            dvs = sets.get(fd.dependent)
            if dvs is None:
                continue
            m = table.fd_code_map(dp.source_col, fd.dependent)
            if m is None:
                continue
            dep_dict = table.dictionaries[fd.dependent]
            dep_codes = np.array(
                sorted(c for v in dvs if (c := dep_dict.id_of(v)) > 0),
                np.int64)
            codes = set(np.nonzero(np.isin(m, dep_codes))[0].tolist())
            codes.discard(0)
            allowed = codes if allowed is None else (allowed & codes)

        if allowed is None or len(allowed) + 1 >= dp.size:
            out.append(dp)
            continue
        codes = sorted(allowed)
        remap = np.zeros(dp.size, np.int32)
        labels = np.empty(len(codes) + 1, object)
        labels[0] = None
        for i, c in enumerate(codes):
            remap[c] = i + 1
            labels[i + 1] = d.values[c - 1]
        from tpu_olap.executor.dimplan import _dim_token
        out.append(DimPlan(dp.name, len(codes) + 1, labels,
                           dp.source_col, "remap",
                           remap_name=pool.add(remap),
                           cache_token=_dim_token("rs", dp.source_col,
                                                  remap)))
    return out


def _lower_agg(query, table, config) -> PhysicalPlan:
    pool = ConstPool()
    intervals, t_min, t_max, empty = _time_range(query, table)
    vexprs = {v.name: v.expression for v in query.virtual_columns}

    bucket_plan = compile_granularity(query.granularity, t_min, t_max,
                                      pool, table.time_boundary)

    if isinstance(query, GroupByQuerySpec):
        dim_specs = query.dimensions
    elif isinstance(query, TopNQuerySpec):
        dim_specs = (query.dimension,)
    else:
        dim_specs = ()
    dim_plans = [compile_dimension(s, table, pool, t_min, t_max,
                                   numeric_dim_budget=config
                                   .numeric_dim_label_budget,
                                   vexprs=vexprs)
                 for s in dim_specs]
    dim_plans = _restrict_dims(dim_plans, query.filter, table, pool)

    agg_plans = compile_aggregations(
        query.aggregations, table, pool, vexprs,
        long_dtype=config.long_dtype, double_dtype=config.double_dtype,
        theta_k_cap=config.theta_k_cap)

    filter_fn = (compile_filter(query.filter, table, pool, vexprs)
                 if query.filter is not None else None)
    imask_fn = _interval_mask_fn(intervals, *table.time_boundary, pool)

    sizes = (bucket_plan.n_buckets,) + tuple(dp.size for dp in dim_plans)
    total = 1
    for s in sizes:
        total *= s
    # sketch aggregates keep [groups × radix] state PER AGGREGATION: at
    # large K their TOTAL dominates memory long before the group COUNT
    # exceeds the dense budget (observed: a 1M-group theta query
    # allocating >100 GB). The mesh's chip-extended partials ([D·K, k]
    # theta tables, executor/sharding.py::mesh_agg_kernel) multiply
    # that state by the mesh size — a fuzz-found sharded theta query
    # ground a host to 100 GB and an XLA rendezvous abort with
    # per-sketch state that looked safe unscaled. Budget the summed,
    # mesh-scaled element count — over budget, the sparse path
    # (clamped sketch width, per-chip fan-out + broker merge) serves it
    # when it can; shapes with no sparse path decline legibly, never
    # allocate
    theta_radix = sum(p.theta_k for p in agg_plans if p.kind == "theta")
    other_radix = sum(_radix(p) for p in agg_plans
                      if p.kind != "theta" and _radix(p) > 1)
    state_radix = other_radix + theta_radix * max(1, _mesh_size(config))
    sketch_over = (state_radix > 0
                   and total * state_radix
                   > config.dense_sketch_state_budget)
    sparse = total > config.dense_group_budget
    if sketch_over and not sparse:
        reject = _sparse_reject_reason(query, total, config)
        if reject is not None:
            raise UnsupportedAggregation(
                f"per-group sketch state {total}×{state_radix} exceeds "
                f"dense_sketch_state_budget "
                f"{config.dense_sketch_state_budget} and {reject}")
        sparse = True
    if sparse:
        # sort-based sparse path (SURVEY.md §8.4 #1)
        reject = _sparse_reject_reason(query, total, config)
        if reject is not None:
            raise UnsupportedAggregation(
                f"group space {total} exceeds dense budget "
                f"{config.dense_group_budget} and {reject}")
        # theta rides the sparse path with a clamped sketch width (the
        # [cap, k] table and its merge transients are per-group state;
        # see EngineConfig.sparse_theta_k_cap)
        import dataclasses as _dc
        agg_plans = tuple(
            _dc.replace(p, theta_k=min(p.theta_k,
                                       config.sparse_theta_k_cap))
            if p.kind == "theta" else p for p in agg_plans)
    if not sparse and not config.enable_x64:
        # sketch state is [groups × radix]; without 64-bit lanes the flat
        # scatter index must fit int32
        for p in agg_plans:
            radix = _radix(p)
            if radix > 1 and total * radix > (1 << 31) - 1:
                raise UnsupportedAggregation(
                    f"sketch index space {total}×{radix} overflows int32 "
                    "without x64")

    pruned_segs = table.prune(
        intervals, _filter_numeric_bounds(query.filter, table, vexprs))
    imask_fn = _elide_covered_imask(imask_fn, pruned_segs, intervals)
    # __time (int64, the widest column) is read only when something
    # actually consumes raw timestamps on device: an un-elided interval
    # mask, or bucketing/timeformat WITHOUT a cached derived id stream
    # (the runner materializes cached streams once per table, so those
    # kernels read [S,R] int32 ids instead of recomputing from millis)
    need_time = ((bucket_plan.kind != "all"
                  and bucket_plan.cache_token is None)
                 or imask_fn is not None
                 or any(dp.kind == "timeformat" and dp.cache_token is None
                        for dp in dim_plans))
    columns, null_cols = _collect_columns(table, query, dim_plans, agg_plans,
                                          vexprs, need_time)
    pruned = [s.meta.segment_id for s in pruned_segs]

    def _masked_key(env, valid, seg_mask, consts, xp, key_builder):
        flat = {c: a.reshape(-1) for c, a in env["cols"].items()}
        nulls = {c: a.reshape(-1) for c, a in env["nulls"].items()}
        materialize_virtuals(vexprs, flat, nulls, xp)
        fenv = {"cols": flat, "nulls": nulls}
        mask = (valid & seg_mask[:, None]).reshape(-1)
        if filter_fn is not None:
            mask = mask & filter_fn(fenv, consts)
        if imask_fn is not None:
            mask = mask & imask_fn(fenv, consts)
        ids, radix = [], []
        if bucket_plan.kind != "all":
            cached = flat.get(bucket_plan.derived_name) \
                if bucket_plan.cache_token else None
            ids.append(bucket_plan.ids_from_cached(cached, consts, xp)
                       if cached is not None
                       else bucket_plan.ids(flat[TIME_COLUMN], consts))
            radix.append(sizes[0])
        for dp, size in zip(dim_plans, sizes[1:]):
            ids.append(dp.ids(fenv, consts, xp))
            radix.append(size)
        if ids:
            key, _ = key_builder(ids, radix, xp)
        else:
            key = xp.zeros(mask.shape, xp.int32)
        return fenv, mask, key

    def kernel(env, valid, seg_mask, consts):
        xp = np if isinstance(valid, np.ndarray) else _jnp()
        fenv, mask, key = _masked_key(env, valid, seg_mask, consts, xp,
                                      build_group_key)
        return group_reduce(key, mask, fenv, agg_plans, total, consts)

    def key_fn(env, valid, seg_mask, consts):
        xp = np if isinstance(valid, np.ndarray) else _jnp()
        return _masked_key(env, valid, seg_mask, consts, xp,
                           build_group_key)

    def make_sparse_kernel(cap):
        from tpu_olap.kernels.sparse_groupby import (build_group_key64,
                                                     sparse_group_reduce)

        def sparse_kernel(env, valid, seg_mask, consts):
            xp = np if isinstance(valid, np.ndarray) else _jnp()
            fenv, mask, key = _masked_key(env, valid, seg_mask, consts, xp,
                                          build_group_key64)
            return sparse_group_reduce(key.astype(xp.int64), mask, fenv,
                                       agg_plans, cap, consts, xp)
        return sparse_kernel

    statics = ("agg", sizes, bucket_plan.kind,
               tuple(dp.kind for dp in dim_plans),
               tuple((p.kind, p.name) for p in agg_plans),
               filter_fn is not None, imask_fn is not None,
               "sparse" if sparse else "dense")

    plan = PhysicalPlan(
        query=query, table=table, kind="agg", pool=pool,
        kernel=None if sparse else kernel,
        statics=statics, dim_plans=dim_plans, bucket_plan=bucket_plan,
        agg_plans=agg_plans, sizes=sizes, total_groups=total,
        pruned_ids=pruned, t_min=t_min, t_max=t_max, empty=empty,
        columns=columns, null_cols=null_cols, virtual_exprs=vexprs,
        filter_streams=_dedupe_streams(pool),
        sparse=sparse, make_sparse_kernel=make_sparse_kernel if sparse
        else None, key_fn=None if sparse else key_fn)
    if not sparse:
        _maybe_use_pallas(plan, query, table, config, filter_fn, imask_fn)
    return plan


def _maybe_use_pallas(plan, query, table, config, filter_fn, imask_fn=None):
    """Swap the generic jnp kernel for the fused Pallas one-hot MXU reduce
    when the plan fits its envelope (kernels.pallas_reduce). The numpy
    ("cpu" platform) path never uses it; "auto" additionally requires the
    TPU backend — interpret mode is for tests ("force"), not production."""
    if config.use_pallas not in ("auto", "force", "never"):
        raise ValueError(
            f"use_pallas must be 'auto', 'force', or 'never'; got "
            f"{config.use_pallas!r}")
    if config.use_pallas == "never" or config.platform == "cpu":
        return
    # cheap backend gate first: under "auto" off-TPU, skip the eligibility
    # scan entirely (it reads per-column min/max metadata)
    on_tpu = _default_backend() == "tpu"
    if config.use_pallas == "auto" and not on_tpu:
        plan.pallas_reason = "auto: backend is not tpu"
        return
    from tpu_olap.kernels import pallas_reduce

    reason = pallas_reduce.eligible(query, plan, table, config, filter_fn)
    if reason is not None:
        plan.pallas_reason = reason
        return
    tuning = _tuned_pallas_policy()
    if (config.use_pallas == "auto" and plan.total_groups == 1
            and tuning.get("auto_ungrouped_pallas") is False):
        # hardware-fitted: with no grouping there is no scatter to beat —
        # XLA's fused masked reduce wins by a fixed dispatch margin
        # (tools/fit_pallas_budget.py, first on-chip A/B)
        plan.pallas_reason = ("auto: ungrouped reduce is faster on the "
                              "generic kernel (hardware-fitted policy)")
        return
    budget = config.pallas_auto_flop_budget
    if budget is None:
        budget = tuning.get("auto_flop_budget")
    if config.use_pallas == "auto" and budget is not None:
        # the one-hot reduce is O(K·n): 2 * n * tile_product FLOPs, where
        # the tile product accounts for the factorized lane packing
        # (docs/PERF_MODEL.md). Past the budget the XLA scatter kernel
        # wins — its work is n-bound and K-free.
        n = len(table.segments) * table.block_rows
        flops = 2.0 * n * pallas_reduce.tile_product(plan, table, config)
        if flops > budget:
            plan.pallas_reason = (
                f"auto: one-hot reduce needs {flops:.2e} FLOPs for "
                f"K={plan.total_groups}; over the auto flop budget")
            return
    plan.kernel = pallas_reduce.build_kernel(plan, table, config, filter_fn,
                                             interpret=not on_tpu,
                                             imask_fn=imask_fn)
    plan.statics = plan.statics + ("pallas", config.pallas_k_per_block)
    plan.pallas_reason = None


def _default_backend() -> str:
    import jax
    return jax.default_backend()


_tuning_cache: dict | None = None
# module constant so tests can monkeypatch the location instead of
# rewriting the shipped fitted file in place
_TUNING_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "planner", "pallas_tuning.json")


def _tuned_pallas_policy() -> dict:
    """Hardware-fitted defaults for the 'auto' Pallas policy:
    tools/fit_pallas_budget.py writes planner/pallas_tuning.json from
    the on-chip A/B pair (docs/PERF_MODEL.md decision procedure #1).
    Keys: auto_ungrouped_pallas (False = K==1 queries take the generic
    fused reduce) and auto_flop_budget (upper cap on the one-hot FLOP
    product; an explicit EngineConfig.pallas_auto_flop_budget overrides
    it). Absent file = empty policy (pre-A/B behavior)."""
    global _tuning_cache
    if _tuning_cache is None:
        import json
        path = _TUNING_PATH
        data = {}
        if os.path.exists(path):
            try:
                with open(path) as f:
                    data = json.load(f)
            except Exception:  # noqa: BLE001 — a bad file must not
                data = {}      # break query planning
        _tuning_cache = data
    return _tuning_cache


def _lower_mask(query, table, config) -> PhysicalPlan:
    """Scan/Select: device computes the row mask; rows assemble host-side."""
    pool = ConstPool()
    intervals, t_min, t_max, empty = _time_range(query, table)
    vexprs = {v.name: v.expression for v in query.virtual_columns}
    filter_fn = (compile_filter(query.filter, table, pool, vexprs)
                 if query.filter is not None else None)
    imask_fn = _interval_mask_fn(intervals, *table.time_boundary, pool)
    pruned_segs = table.prune(
        intervals, _filter_numeric_bounds(query.filter, table, vexprs))
    imask_fn = _elide_covered_imask(imask_fn, pruned_segs, intervals)

    cols: set[str] = set()
    if query.filter is not None:
        cols |= query.filter.columns()
    phys: set[str] = set()
    for c in cols:
        phys |= vexprs[c].columns() if c in vexprs else {c}
    if imask_fn is not None:
        phys.add(TIME_COLUMN)
    unknown = [c for c in phys if c not in table.schema]
    if unknown:
        from tpu_olap.kernels.filtereval import UnsupportedFilter
        raise UnsupportedFilter(f"unknown columns {unknown}")
    null_cols = tuple(sorted(
        c for c in phys if table.schema[c] is not ColumnType.STRING))

    def kernel(env, valid, seg_mask, consts):
        xp = np if isinstance(valid, np.ndarray) else _jnp()
        flat = {c: a.reshape(-1) for c, a in env["cols"].items()}
        nulls = {c: a.reshape(-1) for c, a in env["nulls"].items()}
        materialize_virtuals(vexprs, flat, nulls, xp)
        fenv = {"cols": flat, "nulls": nulls}
        mask = (valid & seg_mask[:, None]).reshape(-1)
        if filter_fn is not None:
            mask = mask & filter_fn(fenv, consts)
        if imask_fn is not None:
            mask = mask & imask_fn(fenv, consts)
        return {"mask": mask}

    statics = ("mask", filter_fn is not None, imask_fn is not None)
    pruned = [s.meta.segment_id for s in pruned_segs]
    return PhysicalPlan(
        query=query, table=table, kind="mask", pool=pool, kernel=kernel,
        statics=statics, pruned_ids=pruned, t_min=t_min, t_max=t_max,
        empty=empty, columns=tuple(sorted(phys)), null_cols=null_cols,
        virtual_exprs=vexprs, filter_streams=_dedupe_streams(pool))


def _dedupe_streams(pool: ConstPool) -> tuple:
    """Unique filter-derived stream requests in first-seen order (the
    same column pair can appear in several conjuncts of one query)."""
    seen, out = set(), []
    for token, src, cname in pool.streams:
        if token not in seen:
            seen.add(token)
            out.append((token, src, cname))
    return tuple(out)


def _jnp():
    import jax.numpy as jnp
    return jnp
