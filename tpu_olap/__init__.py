"""tpu_olap — a TPU-native OLAP query engine.

A from-scratch re-imagining of the capabilities of
``qliro-marketing-services/spark-druid-olap`` (the Sparkline BI Accelerator,
see SURVEY.md): a rewrite-rule planner compiles SQL-shaped logical plans into
a Druid-DSL-like query IR (`tpu_olap.ir`), which lowers to JAX/XLA/Pallas
scan + segmented-reduce programs over dictionary-encoded columnar segments
resident in TPU HBM (`tpu_olap.segments`, `tpu_olap.kernels`,
`tpu_olap.executor`). Partial aggregates merge with XLA collectives over ICI
(`tpu_olap.executor.sharding`); non-rewritable queries fall back to a pandas
interpreter (`tpu_olap.planner.fallback`).

Layer map (SURVEY.md §2 ↔ this package):
  L7 DDL/API            -> tpu_olap.api
  L6 planner/rules      -> tpu_olap.planner
  L5 query IR (DSL)     -> tpu_olap.ir
  L4 relation/metadata  -> tpu_olap.catalog
  L3 execution/dispatch -> tpu_olap.executor
  L2 communication      -> tpu_olap.executor.sharding (XLA collectives)
  L1 storage/scan       -> tpu_olap.segments + tpu_olap.kernels
  L0 raw data/fallback  -> tpu_olap.planner.fallback
"""

__version__ = "0.1.0"

__all__ = ["Engine", "__version__"]


def __getattr__(name):
    if name == "Engine":
        from tpu_olap.api.engine import Engine
        return Engine
    raise AttributeError(name)
