"""Benchmark entry point. Prints ONE JSON line:
{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Flagship metric: worst-case (max) p50 latency across the 13 SSB queries
Q1.1-Q4.3, executed end-to-end through the engine (SQL -> planner ->
lowered jitted program -> device -> result frame). The north-star target is
<500 ms p50 for EVERY query (BASELINE.json:2), so the binding statistic is
the max; vs_baseline = 500 / max_p50 (>1.0 beats the target).

Row count via SSB_ROWS (default 6M = SF1 on an accelerator backend,
200k on CPU); iterations via BENCH_ITERS.

The accelerator backend in this sandbox is reached through a tunnel whose
PJRT client creation can hang indefinitely when the remote side is down.
A bench that hangs produces no number at all, so before touching any jax
backend in-process we probe device initialization in a subprocess with a
hard timeout (BENCH_PROBE_TIMEOUT_S, default 300) and fall back to the CPU
platform when the probe fails — mirroring the engine's own structural
fallback guarantee (SURVEY.md §2: rewrite failure => slow, never an error).
"""

import json
import os
import subprocess
import sys
import time

import numpy as np

TARGET_MS = 500.0


def _probe_default_backend() -> bool:
    """True iff the default (non-cpu-forced) jax backend initializes in a
    fresh subprocess within the timeout."""
    timeout = float(os.environ.get("BENCH_PROBE_TIMEOUT_S", 300))
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax; d = jax.devices(); "
             "print(d[0].platform if d else 'none')"],
            timeout=timeout, capture_output=True, text=True)
        return proc.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def main():
    from tpu_olap.utils.platform import env_flag, force_cpu_platform

    if env_flag("BENCH_FORCE_CPU") or not _probe_default_backend():
        force_cpu_platform()
    import jax

    backend = jax.default_backend()
    default_rows = 6_000_000 if backend != "cpu" else 200_000
    rows = int(os.environ.get("SSB_ROWS", default_rows))
    iters = int(os.environ.get("BENCH_ITERS", 7))

    from tpu_olap import Engine
    from tpu_olap.bench import QUERIES, register_ssb

    eng = Engine()
    register_ssb(eng, lineorder_rows=rows, seed=0)

    detail = {}
    for qname in sorted(QUERIES):
        sql = QUERIES[qname]
        # Warm twice: the first run compiles and observes the true group
        # count, which re-sizes the packed result buffer; the second run
        # compiles the re-sized template so timed runs are all cache hits.
        eng.sql(sql)
        eng.sql(sql)
        assert eng.last_plan.rewritten, (qname,
                                         eng.last_plan.fallback_reason)
        times = []
        for _ in range(iters):
            t0 = time.perf_counter()
            eng.sql(sql)
            times.append((time.perf_counter() - t0) * 1000)
        detail[qname] = round(float(np.percentile(times, 50)), 3)

    worst = max(detail.values())
    print(json.dumps({
        "metric": "ssb_13q_p50_max_ms",
        "value": round(worst, 3),
        "unit": "ms",
        "vs_baseline": round(TARGET_MS / worst, 2),
        "detail": {"rows": rows, "backend": backend,
                   "per_query_p50_ms": detail},
    }))


if __name__ == "__main__":
    main()
