"""Benchmark entry point. Prints ONE JSON line:
{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Current flagship metric: SSB-Q1.1-shaped filtered-sum p50 latency on the
available device. vs_baseline is target_ms / measured_ms against the
driver's 500 ms/query north-star target (BASELINE.json:2) — >1.0 beats it.
This will widen to the full SSB 13-query suite as the engine lands.
"""

import json
import time

import numpy as np

TARGET_MS = 500.0


def main():
    import jax
    import jax.numpy as jnp

    n = 4_000_000
    rng = np.random.default_rng(0)
    price = jnp.asarray(rng.integers(100, 10_000_000, n, dtype=np.int32))
    discount = jnp.asarray(rng.integers(0, 11, n, dtype=np.int32))
    quantity = jnp.asarray(rng.integers(1, 51, n, dtype=np.int32))
    year = jnp.asarray(rng.integers(1992, 1999, n, dtype=np.int32))

    @jax.jit
    def q11(price, discount, quantity, year):
        mask = ((year == 1993) & (discount >= 1) & (discount <= 3)
                & (quantity < 25))
        # float32 on purpose: this placeholder measures scan+reduce latency
        # only; parity-grade (wide-accumulator) summation lives in the engine
        rev = price.astype(jnp.float32) * discount.astype(jnp.float32)
        return jnp.sum(jnp.where(mask, rev, 0.0))

    q11(price, discount, quantity, year).block_until_ready()  # compile
    times = []
    for _ in range(20):
        t0 = time.perf_counter()
        q11(price, discount, quantity, year).block_until_ready()
        times.append((time.perf_counter() - t0) * 1000)
    p50 = float(np.percentile(times, 50))
    print(json.dumps({
        "metric": "ssb_q1.1_shaped_filtered_sum_p50",
        "value": round(p50, 3),
        "unit": "ms",
        "vs_baseline": round(TARGET_MS / p50, 2),
    }))


if __name__ == "__main__":
    main()
