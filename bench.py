"""Benchmark entry point. Prints ONE JSON line:
{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Flagship metric: worst-case (max) p50 latency across the 13 SSB queries
Q1.1-Q4.3, executed end-to-end through the engine (SQL -> planner ->
lowered jitted program -> device -> result frame). The north-star target is
<500 ms p50 for EVERY query (BASELINE.json:2), so the binding statistic is
the max; vs_baseline = 500 / max_p50 (>1.0 beats the target).

Scale: SF1 by default (6M lineorder rows, BASELINE.json SF100's data path
at 1/100th rows) via the multi-file-parquet streaming-ingest path under an
ENFORCED host-RAM cap (RLIMIT_AS, BENCH_RAM_CAP_GB, default 24) and an
explicit HBM budget, with ingest wall time, process peak RSS, and ledger
eviction counts recorded in the detail — the at-scale data-path proof
(SURVEY.md §8.4 #4). Row count via SSB_ROWS, iterations via BENCH_ITERS.
Generated parquet is cached under .ssb_data/ keyed by (rows, seed) so
repeat runs skip generation.

The accelerator backend in this sandbox is reached through a tunnel whose
PJRT client creation can hang indefinitely when the remote side is down.
A bench that hangs produces no number at all, so before touching any jax
backend in-process we probe device initialization in a subprocess with a
hard timeout (BENCH_PROBE_TIMEOUT_S, default 300) and fall back to the CPU
platform when the probe fails — mirroring the engine's own structural
fallback guarantee (SURVEY.md §2: rewrite failure => slow, never an error).
"""

import json
import os
import resource
import subprocess
import sys
import time

import numpy as np

TARGET_MS = 500.0
REPO = os.path.dirname(os.path.abspath(__file__))


def _probe_default_backend() -> str | None:
    """None iff the default (non-cpu-forced) jax backend initializes in a
    fresh subprocess within the timeout; else a legible failure reason
    (stamped into the artifact as "tpu_unavailable" — VERDICT r4 missing
    #1: a CPU number must self-explain why it is not a TPU number)."""
    timeout = float(os.environ.get("BENCH_PROBE_TIMEOUT_S", 300))
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax; d = jax.devices(); "
             "print(d[0].platform if d else 'none')"],
            timeout=timeout, capture_output=True, text=True)
    except subprocess.TimeoutExpired:
        return (f"device-init probe hung past {timeout:.0f}s "
                "(axon tunnel down: PJRT client creation blocks)")
    if proc.returncode != 0:
        tail = proc.stderr.strip()
        msg = f"device-init probe exited rc={proc.returncode}"
        return msg + (f": {tail.splitlines()[-1][:200]}" if tail else "")
    return None


def _peak_rss_mb() -> int:
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss // 1024


def _prepare_dataset(rows: int, seed: int) -> tuple[list, dict]:
    """Generate (or reuse cached) multi-file SSB parquet at `rows` scale.
    Dimension tables are persisted alongside the fact files so the
    cache-hit path reloads the exact frames the fact's foreign keys were
    drawn against (no re-derivation that could drift)."""
    import pandas as pd

    from tpu_olap.bench.ssb import write_ssb_parquet

    data_dir = os.environ.get(
        "SSB_DATA_DIR",
        os.path.join(REPO, ".ssb_data", f"rows{rows}-seed{seed}"))
    manifest = os.path.join(data_dir, "MANIFEST.json")

    def dim_path(t):
        return os.path.join(data_dir, f"dim-{t}.parquet")

    if os.path.exists(manifest):
        with open(manifest) as f:
            m = json.load(f)
        if m.get("rows") == rows and m.get("seed") == seed \
                and m.get("dims") and all(
                os.path.exists(p) for p in m["paths"]) and all(
                os.path.exists(dim_path(t)) for t in m["dims"]):
            dims = {t: pd.read_parquet(dim_path(t)) for t in m["dims"]}
            return m["paths"], dims
    paths, dims = write_ssb_parquet(data_dir, rows, seed=seed)
    for t, df in dims.items():
        df.to_parquet(dim_path(t), index=False)
    with open(manifest, "w") as f:
        json.dump({"rows": rows, "seed": seed, "paths": paths,
                   "dims": sorted(dims)}, f)
    return paths, dims


def _setup(extra_cfg: dict | None = None):
    """Shared bench preamble: backend probe, RAM-capped dataset prep +
    streaming ingest, engine construction. Returns (engine, ctx) where
    ctx carries the numbers both bench modes stamp into artifacts.
    `extra_cfg` overlays EngineConfig fields (the cache bench enables
    the semantic result cache; the latency/throughput benches keep the
    default-off caches so every timed execution measures real
    compute)."""
    from tpu_olap.utils.platform import env_flag, force_cpu_platform

    tpu_unavailable = None
    if env_flag("BENCH_FORCE_CPU"):
        tpu_unavailable = "BENCH_FORCE_CPU=1 (explicit CPU run)"
        force_cpu_platform()
    elif not env_flag("BENCH_SKIP_PROBE"):
        tpu_unavailable = _probe_default_backend()
        if tpu_unavailable is not None:
            force_cpu_platform()
    # BENCH_SKIP_PROBE trusts the default backend directly — used by
    # tools/tpu_probe.py, whose own subprocess timeout replaces the
    # probe (a separate probe process can consume the tunnel's brief
    # up-window before the bench process gets to it)
    import jax

    backend = jax.default_backend()
    if backend == "cpu" and tpu_unavailable is None:
        tpu_unavailable = "default jax backend is cpu (no device plugin)"
    # progress breadcrumbs on STDERR (stdout stays one JSON line): lets
    # the probe loop's timeout log show how far an attempt got
    def note(msg):
        print(f"[bench] {msg}", file=sys.stderr, flush=True)

    note(f"backend={backend}")
    rows = int(os.environ.get("SSB_ROWS", 6_000_000))
    iters = int(os.environ.get("BENCH_ITERS", 5))
    seed = 0

    # Enforced host-RAM cap over the DATA PATH — generation and streaming
    # ingest run under it, so an unbounded materialization crashes the
    # bench rather than silently leaning on a 125 GB host (VERDICT
    # round-2 task #1). The soft limit is restored before the query
    # phase: a finite RLIMIT_AS makes XLA:CPU's arena reservation fail
    # into small-chunk mode, slowing query execution ~1.7x — the cap
    # proves ingest boundedness, not query-allocator behavior.
    cap_gb = float(os.environ.get("BENCH_RAM_CAP_GB", 24))
    cap = int(cap_gb * 2**30)
    soft0, hard0 = resource.getrlimit(resource.RLIMIT_AS)
    if hard0 != resource.RLIM_INFINITY:
        cap = min(cap, hard0)  # soft may never exceed a finite hard limit
    resource.setrlimit(resource.RLIMIT_AS, (cap, hard0))

    from tpu_olap import Engine
    from tpu_olap.bench import QUERIES, register_ssb_parquet
    from tpu_olap.executor import EngineConfig

    t0 = time.perf_counter()
    paths, dims = _prepare_dataset(rows, seed)
    gen_s = time.perf_counter() - t0
    note(f"dataset ready ({gen_s:.1f}s)")

    # HBM budget: enough for the SSB working set but bounded, so the
    # ledger's accounting (and eviction under pressure) is always live.
    hbm_budget = int(os.environ.get(
        "BENCH_HBM_BUDGET_BYTES", 8 * 2**30))
    # SSB_USE_PALLAS=never|force|auto: lets the probe bank a
    # Pallas-vs-XLA-scatter comparison on the same data when the TPU
    # tunnel opens (auto = Pallas on TPU where eligible). Validated
    # HERE: failing after a full ingest (or inside a scarce tunnel
    # up-window) on a typo would waste the run.
    use_pallas = os.environ.get("SSB_USE_PALLAS", "auto")
    if use_pallas not in ("auto", "force", "never"):
        raise SystemExit(
            f"SSB_USE_PALLAS={use_pallas!r}: must be auto|force|never")
    # history_limit raised: the bench slices eng.history by saved offsets
    # (per-phase batch attribution), which a steady-state ring eviction
    # would shift mid-run; the bench process is short-lived anyway
    eng = Engine(EngineConfig(hbm_budget_bytes=hbm_budget,
                              use_pallas=use_pallas,
                              history_limit=1_000_000,
                              **(extra_cfg or {})))
    t0 = time.perf_counter()
    register_ssb_parquet(eng, paths, dims)
    ingest_s = time.perf_counter() - t0
    note(f"ingest done ({ingest_s:.1f}s)")
    ingest_peak_rss_mb = _peak_rss_mb()
    resource.setrlimit(resource.RLIMIT_AS, (soft0, hard0))  # query phase
    seg = eng.catalog.get("lineorder").segments
    stored_mb = sum(c.nbytes for s in seg.segments
                    for c in s.columns.values()) // 2**20
    return eng, {
        "note": note, "backend": backend, "rows": rows, "iters": iters,
        "tpu_unavailable": tpu_unavailable, "use_pallas": use_pallas,
        "cap_gb": cap_gb, "gen_s": gen_s, "ingest_s": ingest_s,
        "ingest_peak_rss_mb": ingest_peak_rss_mb, "stored_mb": stored_mb,
        "hbm_budget": hbm_budget, "paths": paths, "dims": dims,
    }


class _OneDispatchFault:
    """bench --inject-faults: when armed, fail exactly the FIRST
    dispatch attempt of the next query — the retry layer answers, and
    the wall-clock difference vs the clean run is the recovery cost
    (cache purge + re-upload + recompile where needed)."""

    stages = ("dispatch",)

    def __init__(self):
        self.armed = False

    def __call__(self, stage, attempt):
        if self.armed and attempt == 0:
            self.armed = False
            raise RuntimeError("bench-injected dispatch fault")


def _fault_overhead(eng, iters: int, note):
    """Per-query p50 with one injected dispatch fault per execution
    (banked next to the clean p50 so robustness cost shows up in the
    perf trajectory instead of being invisible). Requires
    dispatch_retries >= 1 (the engine default) so the retry — not the
    pandas fallback — answers."""
    from tpu_olap.bench import QUERIES

    inj = _OneDispatchFault()
    prev = eng.config.fault_injector
    eng.config.fault_injector = inj
    fault_ms, fell_back = {}, {}
    try:
        for qname in sorted(QUERIES):
            sql = QUERIES[qname]
            times = []
            n_fb = 0
            for _ in range(iters):
                n0 = len(eng.history)
                inj.armed = True
                t0 = time.perf_counter()
                eng.sql(sql)
                times.append((time.perf_counter() - t0) * 1000)
                n_fb += sum(1 for m in eng.history[n0:]
                            if m.get("query_type") == "fallback")
            fault_ms[qname] = round(float(np.percentile(times, 50)), 3)
            if n_fb:
                fell_back[qname] = n_fb
            note(f"{qname} faulted p50={fault_ms[qname]}ms"
                 + (f" (fallback x{n_fb})" if n_fb else ""))
    finally:
        eng.config.fault_injector = prev
    return fault_ms, fell_back


def main(span_summary: bool = False, inject_faults: int | None = None,
         trace_out: str | None = None,
         pipeline_depth: int | None = None):
    eng, ctx = _setup(
        {} if pipeline_depth is None
        else {"pipeline_depth": pipeline_depth})
    note = ctx["note"]
    backend, rows, iters = ctx["backend"], ctx["rows"], ctx["iters"]
    tpu_unavailable, use_pallas = ctx["tpu_unavailable"], ctx["use_pallas"]

    from tpu_olap.bench import QUERIES
    from tpu_olap.utils.platform import env_flag
    import jax

    # BENCH_RESULT_DIGEST=1 records a per-query sha256 over the rendered
    # result frame — lets two runs of the same scale prove identical
    # answers (e.g. an eviction-churn run vs the default-budget run)
    # without shipping result rows in the artifact.
    want_digest = env_flag("BENCH_RESULT_DIGEST")
    digests = {}

    # Dispatch+fetch round-trip floor: a trivial compiled op, fetched
    # back. Through the axon tunnel this is ~66-68 ms of pure transport;
    # banking it per-artifact makes device-only compute a first-class
    # metric (wall p50 minus the floor) so compute regressions cannot
    # hide under the transport term (VERDICT r4 weak #2).
    import jax.numpy as jnp
    tiny = jax.jit(lambda x: x + 1)
    one = jnp.zeros((8,), jnp.int32)
    np.asarray(tiny(one))  # compile
    rtts = []
    for _ in range(max(iters, 5)):
        t0 = time.perf_counter()
        np.asarray(tiny(one))
        rtts.append((time.perf_counter() - t0) * 1000)
    rtt_floor = round(float(np.percentile(rtts, 50)), 3)
    note(f"rtt_floor={rtt_floor}ms")

    detail = {}
    spread = {}  # per-query min/max over the timed iters (VERDICT r3
    #              weak #2: single-sample artifacts need variance data)
    exec_ms = {}  # per-query engine-recorded execute phase (device
    #               dispatch+fetch, excludes plan/lower/assemble)
    over_floor = {}  # execute minus the transport floor: the honest
    #                  per-query compute term
    phase_ms = {}  # --span-summary: per-query per-phase p50 from the
    #                span tree (obs.trace) — parse/plan/prepare/dispatch/
    #                host-transfer/assemble attribution in the artifact
    slow_traces = {}  # --trace-out: (ms, Trace) of each query's slowest
    #                   timed iteration, exported as one Chrome trace so
    #                   profiles get banked alongside the numbers
    for qname in sorted(QUERIES):
        sql = QUERIES[qname]
        # Warm twice: the first run compiles and observes the true group
        # count, which re-sizes the packed result buffer; the second run
        # compiles the re-sized template so timed runs are all cache hits.
        eng.sql(sql)
        res = eng.sql(sql)
        assert eng.last_plan.rewritten, (qname,
                                         eng.last_plan.fallback_reason)
        if want_digest:
            import hashlib
            digests[qname] = hashlib.sha256(
                res.to_csv(float_format="%.6g").encode()).hexdigest()[:16]
        times = []
        execs = []
        phases: dict = {}
        for _ in range(iters):
            n0 = len(eng.history)
            t0 = time.perf_counter()
            eng.sql(sql)
            times.append((time.perf_counter() - t0) * 1000)
            if trace_out is not None and eng.tracer.last is not None:
                prev = slow_traces.get(qname)
                if prev is None or times[-1] > prev[0]:
                    slow_traces[qname] = (times[-1], eng.tracer.last)
            # only records THIS dispatch appended: a fallback-served
            # iteration must not re-report a stale device timing
            fresh = [m for m in eng.history[n0:] if "execute_ms" in m]
            if fresh:
                execs.append(fresh[-1]["execute_ms"])
            if span_summary and eng.tracer.last is not None:
                from tpu_olap.obs.trace import phase_totals
                for ph, ms in phase_totals(eng.tracer.last).items():
                    phases.setdefault(ph, []).append(ms)
        if span_summary:
            phase_ms[qname] = {
                ph: round(float(np.percentile(v, 50)), 3)
                for ph, v in sorted(phases.items())}
        detail[qname] = round(float(np.percentile(times, 50)), 3)
        spread[qname] = {"min": round(min(times), 3),
                         "max": round(max(times), 3)}
        if execs:
            exec_ms[qname] = round(float(np.percentile(execs, 50)), 3)
            over_floor[qname] = round(max(0.0, exec_ms[qname] - rtt_floor),
                                      3)
        note(f"{qname} p50={detail[qname]}ms "
             f"[{spread[qname]['min']}..{spread[qname]['max']}] "
             f"exec={exec_ms.get(qname)}ms")

    if trace_out is not None:
        # one Chrome-trace file with each flight's slowest query as its
        # own named row — open in Perfetto next to the BENCH json
        from tpu_olap.obs.profile import chrome_trace
        traces = [slow_traces[q][1] for q in sorted(slow_traces)]
        with open(trace_out, "w") as f:
            json.dump(chrome_trace(traces), f)
        note(f"chrome trace written: {trace_out} "
             f"({len(traces)} slowest-iteration traces)")

    fault_detail = None
    if inject_faults:
        fault_ms, fell_back = _fault_overhead(eng, inject_faults, note)
        overhead = {q: round(max(0.0, fault_ms[q] - detail[q]), 3)
                    for q in fault_ms}
        fault_detail = {
            "iters": inject_faults,
            "per_query_p50_fault_ms": fault_ms,
            "per_query_recovery_overhead_ms": overhead,
            "worst_recovery_overhead_ms": round(
                max(overhead.values()), 3),
            **({"fallback_served": fell_back} if fell_back else {}),
        }

    ledger = eng.runner._hbm_ledger
    worst = max(detail.values())
    print(json.dumps({
        "metric": "ssb_13q_p50_max_ms",
        "value": round(worst, 3),
        "unit": "ms",
        "vs_baseline": round(TARGET_MS / worst, 2),
        "detail": {
            "rows": rows, "backend": backend,
            "use_pallas": use_pallas,
            **({"tpu_unavailable": tpu_unavailable}
               if tpu_unavailable else {}),
            "rtt_floor_ms": rtt_floor,
            "per_query_p50_ms": detail,
            "per_query_spread_ms": spread,
            "per_query_execute_ms": exec_ms,
            "per_query_over_floor_ms": over_floor,
            "worst_over_floor_ms": round(max(over_floor.values()), 3)
            if over_floor else None,
            "iters": iters,
            "ram_cap_gb": ctx["cap_gb"],
            "generate_s": round(ctx["gen_s"], 1),
            "ingest_s": round(ctx["ingest_s"], 1),
            "ingest_peak_rss_mb": ctx["ingest_peak_rss_mb"],
            "segment_store_mb": ctx["stored_mb"],
            "hbm": {"budget_bytes": ctx["hbm_budget"],
                    "bytes_in_use": ledger.bytes_in_use,
                    "evictions": ledger.evictions,
                    # telemetry-plane census (ISSUE 17): high-watermark
                    # growth between runs is a regression the compare
                    # gate catches even when steady-state bytes match
                    "high_watermark_bytes": ledger.watermarks()["total"],
                    "per_chip_high_watermark_bytes":
                        ledger.watermarks()["per_chip"]},
            "alerts": eng.runner.sentinel.counts(),
            **({"per_query_phase_p50_ms": phase_ms}
               if span_summary else {}),
            **({"trace_out": trace_out} if trace_out else {}),
            **({"fault_injection": fault_detail}
               if fault_detail else {}),
            **({"result_digests": digests} if want_digest else {}),
        },
    }))


def _concurrency_main(n_clients: int) -> int:
    """`bench.py --concurrency N`: shared-scan batch throughput A/B.

    N clients replay the 13-query SSB dashboard loop concurrently — the
    broker scenario the batch executor exists for (every user's panel
    refresh re-issues the same queries). Phase A dispatches them
    sequentially (the dispatch lock serializes: N concurrent queries =
    N full scans). Phase B turns on the request coalescer
    (EngineConfig.batch_window_ms): concurrent callers ride ONE fused
    shared-scan dispatch — identical in-flight queries scan once,
    distinct compatible ones fuse into one device pass. Banks the
    throughput ratio to BENCH_BATCH.json with per-query parity checked
    against the sequential-path oracle (frame.equals — bitwise)."""
    import threading

    eng, ctx = _setup()
    note = ctx["note"]
    from tpu_olap.bench import QUERIES
    qnames = sorted(QUERIES)
    rounds = int(os.environ.get("BENCH_CONC_ROUNDS", 3))
    # window sized to re-capture the whole client cohort after each
    # batch completes (clients wake together, then spend ~10-40 ms of
    # GIL-bound frame conversion before re-submitting): ~25 ms keeps
    # the dashboard loop in lockstep, so batches stay large and mostly
    # identical (dedupe, no fresh fused compiles); 5 ms shears the
    # cohort into small mixed batches
    window_ms = float(os.environ.get("BENCH_BATCH_WINDOW_MS", 25.0))

    # warm twice (compile + packed-cap resize) and keep the sequential
    # result as the parity oracle
    ref = {}
    for qn in qnames:
        eng.sql(QUERIES[qn])
        ref[qn] = eng.sql(QUERIES[qn])
        assert eng.last_plan.rewritten, (qn,
                                         eng.last_plan.fallback_reason)

    def run_phase(tag, timed_rounds):
        errs, frames = [], {}

        def client(ci):
            for _ in range(timed_rounds):
                for qn in qnames:
                    try:
                        frames[(ci, qn)] = eng.sql(QUERIES[qn])
                    except Exception as e:  # noqa: BLE001 — banked
                        errs.append((qn, repr(e)))

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(n_clients)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        n = n_clients * timed_rounds * len(qnames)
        note(f"{tag}: {n} queries in {wall:.1f}s ({n / wall:.1f} qps), "
             f"errors={len(errs)}")
        return wall, n, frames, errs

    eng.runner.set_batch_window(0)
    wall_seq, n_seq, frames_seq, errs_seq = run_phase("sequential", rounds)
    eng.runner.set_batch_window(window_ms)
    run_phase("batched-warmup", 1)  # compile common fused compositions
    h0 = len(eng.history)
    wall_bat, n_bat, frames_bat, errs_bat = run_phase("batched", rounds)
    hist = eng.history[h0:]

    bad = sorted({k[1] for k, f in frames_bat.items()
                  if not f.equals(ref[k[1]])})
    seq_bad = sorted({k[1] for k, f in frames_seq.items()
                      if not f.equals(ref[k[1]])})
    batches = {}
    for m in hist:
        if "batch_id" in m:
            batches.setdefault(m["batch_id"], []).append(m)
    n_dedup = sum(1 for m in hist if m.get("batch_dedup"))
    sizes = [recs[0]["batch_size"] for recs in batches.values()]
    shared = [recs[0].get("scan_ms_shared", 0.0)
              for recs in batches.values()]
    agg = [m.get("agg_ms", 0.0) for m in hist if "agg_ms" in m]

    qps_seq = n_seq / wall_seq
    qps_bat = n_bat / wall_bat
    speedup = qps_bat / qps_seq
    parity_ok = not bad and not seq_bad and not errs_seq and not errs_bat
    out = {
        "metric": f"ssb_batch_throughput_speedup_c{n_clients}",
        "value": round(speedup, 2),
        "unit": "x",
        # target: >= 2x aggregate throughput at this concurrency
        "vs_baseline": round(speedup / 2.0, 2),
        "detail": {
            "rows": ctx["rows"], "backend": ctx["backend"],
            **({"tpu_unavailable": ctx["tpu_unavailable"]}
               if ctx["tpu_unavailable"] else {}),
            "concurrency": n_clients, "rounds": rounds,
            "batch_window_ms": window_ms,
            "sequential": {"queries": n_seq, "wall_s": round(wall_seq, 2),
                           "qps": round(qps_seq, 2),
                           "errors": len(errs_seq)},
            "batched": {"queries": n_bat, "wall_s": round(wall_bat, 2),
                        "qps": round(qps_bat, 2),
                        "errors": len(errs_bat)},
            "parity_ok": parity_ok,
            "parity_mismatch_queries": bad,
            "batches": len(batches),
            "batch_size_mean": round(float(np.mean(sizes)), 2)
            if sizes else None,
            "batch_size_max": max(sizes) if sizes else None,
            "deduped_queries": n_dedup,
            "fused_dispatches": sum(
                1 for recs in batches.values()
                if recs[0].get("batch_legs", 1) > 1),
            "fused_compiles": sum(
                1 for recs in batches.values()
                if recs[0].get("batch_legs", 1) > 1
                and not recs[0].get("jit_cache_hit")),
            "scan_ms_shared_total": round(float(np.sum(shared)), 1),
            "agg_ms_total": round(float(np.sum(agg)), 1),
        },
    }
    with open(os.path.join(REPO, "BENCH_BATCH.json"), "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))
    return 0 if parity_ok else 1


def _cache_main(mode: str) -> int:
    """`bench.py --cache-mode cold|warm|mixed`: the semantic-result-
    cache A/B (docs/CACHING.md). COLD is the honest baseline — both
    tiers DISABLED, so it equals the plain latency bench's execution
    and tier-1 population overhead cannot inflate the speedup. WARM
    enables the caches, primes, and times repeats (tier-2 serving);
    the headline value is worst cold p50 / worst warm p50. Mode
    `mixed` adds two more phases: a WINDOW SWEEP that slides a time
    window across the fact table with tier 2 off, so the per-segment
    tier's partial-recompute path is exercised warm (hits > 0 banked),
    and a FRESH-INGEST phase that re-registers a modified dataset (a
    file subset — genuinely different rows) and proves the
    invalidation contract: zero stale hits, recompute answers matching
    the independent pandas fallback. Parity (`bench.parity`) is
    checked in every state so a cache bug that serves a stale or
    mis-merged result fails the artifact, not just a unit test."""
    from tpu_olap.bench import QUERIES, register_ssb_parquet
    from tpu_olap.bench.parity import ParityError, check_query

    eng, ctx = _setup()  # caches start OFF: the cold phase is honest
    note = ctx["note"]
    iters = ctx["iters"]
    qnames = sorted(QUERIES)
    cfg = eng.config
    rc = eng.runner.result_cache

    def set_tiers(full: bool, segment: bool):
        # ResultCache reads the live config, so flipping the knobs
        # switches tiers between phases without rebuilding the engine
        cfg.result_cache_enabled = full
        cfg.segment_cache_enabled = segment

    # warm the compile caches so cold numbers measure scans, not XLA
    # builds (same convention as the latency bench)
    for qn in qnames:
        eng.sql(QUERIES[qn])
        eng.sql(QUERIES[qn])
        assert eng.last_plan.rewritten, (qn, eng.last_plan.fallback_reason)

    def timed_runs(qn, n):
        times, hits = [], 0
        for _ in range(n):
            n0 = len(eng.history)
            t0 = time.perf_counter()
            eng.sql(QUERIES[qn])
            times.append((time.perf_counter() - t0) * 1000)
            hits += sum(1 for m in eng.history[n0:] if m.get("cache_hit"))
        return times, hits

    cold, warm, hit_rate_cold, hit_rate_warm = {}, {}, {}, {}
    parity = {"cold": True, "warm": True, "window_sweep": None,
              "fresh_ingest": None}
    parity_errors = []

    def check_parity(tag, sql):
        try:
            check_query(eng, sql, label=tag)
            return True
        except ParityError as e:
            parity_errors.append(str(e)[:300])
            return False

    for qn in qnames:
        times, hits = timed_runs(qn, iters)
        cold[qn] = round(float(np.percentile(times, 50)), 3)
        hit_rate_cold[qn] = round(hits / iters, 3)
        if not check_parity(f"cold:{qn}", QUERIES[qn]):
            parity["cold"] = False
        note(f"{qn} cold p50={cold[qn]}ms")

    if mode in ("warm", "mixed"):
        set_tiers(True, True)
        for qn in qnames:
            eng.sql(QUERIES[qn])  # prime
            times, hits = timed_runs(qn, iters)
            warm[qn] = round(float(np.percentile(times, 50)), 3)
            hit_rate_warm[qn] = round(hits / iters, 3)
            if not check_parity(f"warm:{qn}", QUERIES[qn]):
                parity["warm"] = False
            note(f"{qn} warm p50={warm[qn]}ms "
                 f"(hit rate {hit_rate_warm[qn]})")

    sweep = None
    if mode == "mixed":
        # tier-1 window sweep: tier 2 OFF so repeats cannot shortcut to
        # the full-result tier; a monthly-advancing window over the
        # fact table makes each step a PARTIAL tier-1 hit (the overlap
        # serves from cached per-segment partials, only the new tail
        # recomputes in one device pass)
        set_tiers(False, True)
        # month-partitioned re-ingest: the sweep's month-boundary
        # windows then COVER whole segments, which is what makes the
        # per-segment tier able to store/serve them (auto partitioning
        # at small scales resolves coarser and every segment would
        # straddle the window edge)
        t0 = time.perf_counter()
        eng.register_table("lineorder", list(ctx["paths"]),
                           time_column="lo_orderdate_ts",
                           time_partition="month")
        note(f"sweep re-ingest (month partitions): "
             f"{time.perf_counter() - t0:.1f}s")
        rc.clear()
        wsql = ("SELECT d_year, sum(lo_revenue) AS rev FROM lineorder "
                "WHERE lo_orderdate_ts >= TIMESTAMP '{lo}' AND "
                "lo_orderdate_ts < TIMESTAMP '{hi}' "
                "GROUP BY d_year ORDER BY d_year")
        windows = [(f"1993-{m:02d}-01",
                    f"1994-{m:02d}-01") for m in range(1, 7)]
        steps, sweep_ok = [], True
        for i, (lo, hi) in enumerate(windows):
            sql = wsql.format(lo=lo, hi=hi)
            n0 = len(eng.history)
            t0 = time.perf_counter()
            eng.sql(sql)
            ms = (time.perf_counter() - t0) * 1000
            recs = [m for m in eng.history[n0:]
                    if "segments_computed" in m]
            rec = recs[-1] if recs else {}
            steps.append({
                "window": f"{lo}/{hi}", "ms": round(ms, 3),
                "segments_cached": rec.get("segments_cached", 0),
                "segments_computed": rec.get("segments_computed", 0)})
            if not check_parity(f"sweep:{i}", sql):
                sweep_ok = False
        served = sum(st["segments_cached"] for st in steps[1:])
        parity["window_sweep"] = sweep_ok and served > 0
        sweep = {"steps": steps,
                 "segments_served_from_cache": served,
                 "first_step_ms": steps[0]["ms"],
                 "steady_p50_ms": round(float(np.percentile(
                     [st["ms"] for st in steps[1:]], 50)), 3)}
        note(f"window sweep: {served} segment serves from cache, "
             f"first={sweep['first_step_ms']}ms "
             f"steady p50={sweep['steady_p50_ms']}ms")

    fresh = None
    if mode == "mixed":
        # fresh ingest with genuinely different data: a subset of the
        # parquet files (every SF1+ dataset has several). A stale cache
        # entry served after this would answer from the OLD rows and
        # fail parity against the fallback, which reads the new frame.
        set_tiers(True, True)
        paths = ctx["paths"]
        sub = paths[:-1] if len(paths) > 1 else paths
        t0 = time.perf_counter()
        register_ssb_parquet(eng, sub, ctx["dims"])
        reingest_s = time.perf_counter() - t0
        stale_hits = 0
        fresh_ok = True
        fresh_ms = {}
        for qn in qnames:
            n0 = len(eng.history)
            t0 = time.perf_counter()
            eng.sql(QUERIES[qn])
            fresh_ms[qn] = round((time.perf_counter() - t0) * 1000, 3)
            stale_hits += sum(1 for m in eng.history[n0:]
                              if m.get("cache_hit"))
            if not check_parity(f"fresh:{qn}", QUERIES[qn]):
                fresh_ok = False
        parity["fresh_ingest"] = fresh_ok and stale_hits == 0
        fresh = {"files": len(sub), "reingest_s": round(reingest_s, 1),
                 "stale_hits": stale_hits,
                 "per_query_p50_ms": fresh_ms}
        note(f"fresh-ingest: stale_hits={stale_hits} parity={fresh_ok}")

    worst_cold = max(cold.values())
    parity_ok = all(v for v in parity.values() if v is not None)
    if warm:
        speedup = {qn: round(cold[qn] / max(warm[qn], 1e-3), 2)
                   for qn in warm}
        worst_warm = max(warm.values())
        metric = "ssb_cache_warm_speedup"
        value = round(worst_cold / worst_warm, 2)
        vs_baseline = round(value / 5.0, 2)  # target: >= 5x (ISSUE 9)
    else:
        # cold-only mode measures the baseline, not a speedup: bank it
        # under its own metric name instead of a misleading 0x
        speedup, metric = {}, "ssb_cache_cold_p50_max_ms"
        value = round(worst_cold, 3)
        vs_baseline = round(TARGET_MS / worst_cold, 2)
    out = {
        "metric": metric,
        "value": value,
        "unit": "x" if warm else "ms",
        "vs_baseline": vs_baseline,
        "detail": {
            "mode": mode, "rows": ctx["rows"], "iters": iters,
            "backend": ctx["backend"],
            **({"tpu_unavailable": ctx["tpu_unavailable"]}
               if ctx["tpu_unavailable"] else {}),
            # cold == plain execution (caches off): comparable to the
            # latency bench's per-query p50s
            "per_query_p50_ms": cold,
            "cache": {
                "per_query_cold_p50_ms": cold,
                "per_query_warm_p50_ms": warm,
                "per_query_speedup": speedup,
                "min_speedup": min(speedup.values()) if speedup else None,
                "per_query_hit_rate": hit_rate_warm,
                "per_query_cold_hit_rate": hit_rate_cold,
            },
            "parity": parity,
            "parity_ok": parity_ok,
            **({"parity_errors": parity_errors[:5]}
               if parity_errors else {}),
            **({"segment_tier_window_sweep": sweep} if sweep else {}),
            **({"fresh_ingest": fresh} if fresh else {}),
            "cache_snapshot": rc.snapshot(),
        },
    }
    with open(os.path.join(REPO, "BENCH_CACHE.json"), "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))
    return 0 if parity_ok else 1


def _cube_main(mode: str) -> int:
    """`bench.py --cube-mode off|auto`: the materialized-rollup A/B
    (docs/CUBES.md). BASE is the honest floor — the rewrite pass
    disabled and both semantic-cache tiers off, so every timed run is a
    real base-table execution. AUTO then closes the advisor loop on the
    bench's own traffic: the warm-up runs populate the workload
    profiler, `cube_specs_from_workload` turns its ranked rollup
    recommendations into specs, the materializer builds them, and the
    same 13 SSB queries re-run — queries the rewrite covers serve from
    cube partials (path="cube"). Banks BENCH_CUBES.json with per-query
    base-vs-cube p50, materialization cost + storage bytes, coverage,
    and parity: sha256 result digests must MATCH the base path exactly
    for the all-integer SSB aggregates, and every covered query is
    additionally checked against the independent pandas fallback."""
    import hashlib

    from tpu_olap.bench import QUERIES
    from tpu_olap.bench.parity import ParityError, check_query

    eng, ctx = _setup({"cube_auto_refresh": False})
    note = ctx["note"]
    iters = ctx["iters"]
    qnames = sorted(QUERIES)
    eng.config.cube_rewrite_enabled = False

    def digest(frame) -> str:
        return hashlib.sha256(
            frame.to_csv(float_format="%.6g").encode()).hexdigest()[:16]

    # warm compiles AND the workload profiler (the advisor's demand
    # signal is the bench's own traffic — the loop the ISSUE closes)
    for qn in qnames:
        eng.sql(QUERIES[qn])
        eng.sql(QUERIES[qn])
        assert eng.last_plan.rewritten, (qn, eng.last_plan.fallback_reason)

    def timed(qn, n):
        times = []
        cube_serves = 0
        for _ in range(n):
            n0 = len(eng.history)
            t0 = time.perf_counter()
            eng.sql(QUERIES[qn])
            times.append((time.perf_counter() - t0) * 1000)
            cube_serves += sum(1 for m in eng.history[n0:]
                               if m.get("path") == "cube")
        return times, cube_serves

    base, base_digest = {}, {}
    for qn in qnames:
        times, _ = timed(qn, iters)
        base[qn] = round(float(np.percentile(times, 50)), 3)
        base_digest[qn] = digest(eng.sql(QUERIES[qn]))
        note(f"{qn} base p50={base[qn]}ms")

    out = {
        "metric": "ssb_cube_base_p50_max_ms",
        "value": round(max(base.values()), 3),
        "unit": "ms",
        "vs_baseline": round(TARGET_MS / max(base.values()), 2),
        "detail": {
            "mode": mode, "rows": ctx["rows"], "iters": iters,
            "backend": ctx["backend"],
            **({"tpu_unavailable": ctx["tpu_unavailable"]}
               if ctx["tpu_unavailable"] else {}),
            "per_query_base_p50_ms": base,
        },
    }
    if mode == "auto":
        from tpu_olap.cubes import cube_specs_from_workload
        rows = eng.runner.workload.snapshot()
        specs, notes = cube_specs_from_workload(rows, eng,
                                                top=len(qnames))
        t0 = time.perf_counter()
        built, build_errors = [], {}
        for s in specs:
            try:
                e = eng.create_cube(s)
                built.append(s.name)
                note(f"built {s.name}: {e.data.n_rows} rows @ "
                     f"{s.granularity} in {e.build_ms:.0f}ms")
            except Exception as ex:  # noqa: BLE001 — per-spec isolation
                build_errors[s.name] = f"{type(ex).__name__}: {ex}"
                note(f"build FAILED {s.name}: {build_errors[s.name]}")
        build_s = time.perf_counter() - t0

        eng.config.cube_rewrite_enabled = True
        # the independent pandas-fallback oracle is O(full scan) per
        # query — affordable at SF1, hours at SF10+. Digest equality
        # against the base device path is checked at EVERY scale.
        deep_parity = ctx["rows"] <= 10_000_000
        cube_ms, covered, digest_ok, parity_errors = {}, [], {}, []
        speedup = {}
        for qn in qnames:
            eng.sql(QUERIES[qn])  # settle (fold layout warm)
            times, serves = timed(qn, iters)
            cube_ms[qn] = round(float(np.percentile(times, 50)), 3)
            is_covered = serves == iters
            digest_ok[qn] = digest(eng.sql(QUERIES[qn])) \
                == base_digest[qn]
            if is_covered:
                covered.append(qn)
                speedup[qn] = round(base[qn] / max(cube_ms[qn], 1e-3),
                                    2)
                if deep_parity:
                    try:
                        check_query(eng, QUERIES[qn],
                                    label=f"cube:{qn}")
                    except ParityError as e:
                        parity_errors.append(str(e)[:300])
            note(f"{qn} cube p50={cube_ms[qn]}ms covered={is_covered} "
                 f"digest_ok={digest_ok[qn]}"
                 + (f" speedup={speedup.get(qn)}x" if is_covered
                    else ""))
        parity_ok = all(digest_ok.values()) and not parity_errors \
            and bool(covered)
        worst_speedup = min(speedup.values()) if speedup else 0.0
        snap = eng.cubes.snapshot()  # after serving: serve_count live
        storage = sum(r["storage_bytes"] + r["sketch_bytes"]
                      for r in snap if r["status"] == "ready")
        out["metric"] = "ssb_cube_covered_speedup_min"
        out["value"] = worst_speedup
        out["unit"] = "x"
        out["vs_baseline"] = round(worst_speedup / 10.0, 2)  # >=10x
        out["detail"].update({
            "deep_parity_vs_fallback": deep_parity,
            "advisor_specs": len(specs),
            "advisor_notes": notes,
            "cubes_built": built,
            **({"build_errors": build_errors} if build_errors else {}),
            "materialize_s": round(build_s, 2),
            "cube_storage_bytes": storage,
            "cubes": snap,
            "per_query_cube_p50_ms": cube_ms,
            "per_query_speedup": speedup,
            "covered_queries": covered,
            "uncovered_queries": [q for q in qnames
                                  if q not in covered],
            "digest_match": digest_ok,
            "parity_ok": parity_ok,
            **({"parity_errors": parity_errors[:5]}
               if parity_errors else {}),
        })
    with open(os.path.join(REPO, "BENCH_CUBES.json"), "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))
    if mode != "auto":
        return 0
    return 0 if parity_ok else 1


def _mesh_main(n_devices: int) -> int:
    """`bench.py --mesh N`: the sharded-serving A/B (docs/TPU_NOTES.md
    "sharded serving"), banking MULTICHIP_r06.json. The 13 SSB queries
    run against the SAME in-memory denormalized fact on (a) one device
    and (b) an N-chip mesh (jit + NamedSharding, interleaved segment
    placement, cost-model merge strategy), with a sha256 digest over
    every rendered result frame — the mesh answers must be IDENTICAL
    (exact aggs bit-exact, sketch states losslessly merged at the
    broker). On real hardware the mesh is the physical chips; without
    one the host platform is forced to N virtual CPU devices, which
    proves placement/merge/pruning correctness but shares one socket's
    FLOPs — virtual-mesh speedups are parity evidence, not hardware
    scaling (`virtual_mesh: true` in the artifact). Knobs:
    MULTICHIP_ROWS (default 1M), BENCH_ITERS."""
    import hashlib

    from tpu_olap.utils.platform import (ensure_host_device_count,
                                         force_cpu_platform)

    tpu_unavailable = None
    from tpu_olap.utils.platform import env_flag
    if env_flag("BENCH_FORCE_CPU"):
        tpu_unavailable = "BENCH_FORCE_CPU=1 (explicit CPU run)"
    elif not env_flag("BENCH_SKIP_PROBE"):
        tpu_unavailable = _probe_default_backend()
    if tpu_unavailable is not None:
        # no accelerator: build the mesh from virtual host devices
        # (must happen before jax initializes its backends)
        ensure_host_device_count(n_devices)
        force_cpu_platform()
    import jax
    if len(jax.devices()) < n_devices:
        print(json.dumps({"metric": "multichip_worst_p50",
                          "value": None, "unit": "ms",
                          "error": f"only {len(jax.devices())} devices "
                                   f"for --mesh {n_devices}"}))
        return 1

    from tpu_olap import Engine
    from tpu_olap.bench import QUERIES
    from tpu_olap.bench.ssb import generate_tables, register_ssb
    from tpu_olap.executor import EngineConfig

    rows = int(os.environ.get("MULTICHIP_ROWS",
                              os.environ.get("SSB_ROWS", 1_000_000)))
    iters = int(os.environ.get("BENCH_ITERS", 3))
    t_ing = time.perf_counter()
    tables = generate_tables(rows, seed=0)
    e1 = Engine(EngineConfig())
    en = Engine(EngineConfig(num_shards=n_devices))
    for e in (e1, en):
        register_ssb(e, tables, block_rows=1 << 13)
    ingest_s = time.perf_counter() - t_ing

    def digest(frame):
        return hashlib.sha256(
            frame.to_csv(float_format="%.6g").encode()).hexdigest()[:16]

    def p50_of(eng, sql):
        eng.sql(sql)          # compile + cap observation
        res = eng.sql(sql)    # re-sized template compile
        times = []
        for _ in range(iters):
            t0 = time.perf_counter()
            eng.sql(sql)
            times.append((time.perf_counter() - t0) * 1000)
        return res, round(float(np.percentile(times, 50)), 3)

    per_query = {}
    parity_ok = True
    mesh_records = {}
    for qname in sorted(QUERIES):
        sql = QUERIES[qname]
        r1, p1 = p50_of(e1, sql)
        rn, pn = p50_of(en, sql)
        rewritten = bool(en.last_plan.rewritten)
        d1, dn = digest(r1), digest(rn)
        match = d1 == dn
        parity_ok = parity_ok and match and rewritten
        m = dict(en.runner.history[-1])
        mesh_records[qname] = m
        per_query[qname] = {
            "p50_1dev_ms": p1, "p50_mesh_ms": pn,
            "speedup": round(p1 / pn, 3) if pn > 0 else None,
            "digest": dn, "digest_match": match,
            "rewritten": rewritten,
            "num_shards": m.get("num_shards"),
            "merge": m.get("merge"),
            "strategy": (m.get("cost") or {}).get("strategy"),
            "segments_window_per_chip":
                m.get("segments_window_per_chip"),
        }
        print(f"[mesh] {qname}: 1dev={p1}ms mesh={pn}ms "
              f"{'OK' if match else 'DIGEST MISMATCH'}",
              file=sys.stderr)

    # scan-bound headline: queries whose pruned set still covers the
    # table (no per-chip window) — the shapes per-chip bandwidth scales
    # directly. Flight-1 queries are PRUNING-bound instead (manifest
    # pruning + the per-chip window already cut them to a handful of
    # segments; at single-digit ms the mesh dispatch overhead
    # dominates), so they are reported but not in the scaling headline.
    sb = [v["speedup"] for v in per_query.values()
          if v["speedup"] and v.get("segments_window_per_chip") is None]
    worst = max(v["p50_mesh_ms"] for v in per_query.values())
    out = {
        "metric": "multichip_worst_p50",
        "value": worst,
        "unit": "ms",
        "vs_baseline": round(TARGET_MS / worst, 3) if worst else None,
        "mode": "multichip",
        "n_devices": n_devices,
        "rows": rows,
        "iters": iters,
        "ingest_s": round(ingest_s, 1),
        "backend": jax.default_backend(),
        "virtual_mesh": tpu_unavailable is not None,
        **({"tpu_unavailable": tpu_unavailable}
           if tpu_unavailable else {}),
        "parity_ok": parity_ok,
        "scan_bound_speedup_min": round(min(sb), 3) if sb else None,
        "per_query": per_query,
    }
    with open(os.path.join(REPO, "MULTICHIP_r06.json"), "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))
    return 0 if parity_ok else 1


def _ingest_main() -> int:
    """`bench.py --ingest-mode`: the real-time ingest bench
    (docs/INGEST.md), banking BENCH_INGEST.json. Synthetic fact table
    (INGEST_BASE_ROWS, default 200k — append throughput and
    query-under-ingest interference do not need SF scale) with a WAL
    in a temp dir, then four phases:

    1. QUIESCED query p50/p99 — the interference baseline;
    2. SUSTAINED APPEND throughput: INGEST_BATCH_ROWS-row batches for
       INGEST_SECONDS with the background compactor live (rows/s
       includes WAL fsync + snapshot swap + backpressure waits);
    3. QUERY UNDER INGEST: the same query timed while an appender
       thread streams batches — p50/p99 vs quiesced is the write-path
       interference the enqueue-only dispatch lock is supposed to
       bound;
    4. CRASH RECOVERY: a fresh engine re-registers the base and
       replays the WAL — replay wall + rows/s, then compaction wall;
    5. CHECKPOINTED RECOVERY (docs/DURABILITY.md): checkpoint the
       recovered table (seal + spill + manifest + WAL truncation),
       append a small tail, crash again — the restart must replay
       ONLY the tail, so its replay cost is independent of the
       pre-checkpoint append volume (banked as frames full vs tail).

    Parity: the final recovered state must be sha256-identical to a
    one-shot registration of base + every acknowledged batch."""
    import hashlib
    import shutil
    import tempfile
    import threading

    import pandas as pd

    from tpu_olap import Engine
    from tpu_olap.executor import EngineConfig
    from tpu_olap.resilience.errors import IngestBackpressure

    def note(msg):
        print(f"[bench] {msg}", file=sys.stderr, flush=True)

    base_rows = int(os.environ.get("INGEST_BASE_ROWS", 200_000))
    batch_rows = int(os.environ.get("INGEST_BATCH_ROWS", 1_000))
    run_s = float(os.environ.get("INGEST_SECONDS", 3.0))
    iters = int(os.environ.get("BENCH_ITERS", 5)) * 8
    fsync = os.environ.get("INGEST_WAL_FSYNC", "always")

    rng = np.random.default_rng(0)
    base = pd.DataFrame({
        "ts": pd.to_datetime("1993-01-01") + pd.to_timedelta(
            rng.integers(0, 86400 * 365, base_rows), unit="s"),
        "cat": rng.choice([f"c{i:02d}" for i in range(32)], base_rows),
        "v": rng.integers(0, 10_000, base_rows).astype(np.int64),
    })
    wal_dir = tempfile.mkdtemp(prefix="bench-ingest-wal-")
    store_dir = tempfile.mkdtemp(prefix="bench-ingest-store-")
    # checkpoint_on_compact stays OFF so phases 1-4 measure the pure
    # WAL-replay path (the honest O(total) baseline phase 5 is
    # compared against); phase 5 checkpoints explicitly
    mk_cfg = lambda: EngineConfig(  # noqa: E731
        ingest_wal_dir=wal_dir, ingest_wal_fsync=fsync,
        ingest_store_dir=store_dir,
        ingest_store_checkpoint_on_compact=False,
        ingest_compact_rows=1 << 15, ingest_compact_interval_s=0.25,
        history_limit=1_000_000)
    eng = Engine(mk_cfg())
    t0 = time.perf_counter()
    eng.register_table("events", base, time_column="ts",
                       block_rows=1 << 14, time_partition="month")
    note(f"base ingest: {base_rows} rows in "
         f"{time.perf_counter() - t0:.2f}s")
    q = ("SELECT cat, count(*) AS n, sum(v) AS s FROM events "
         "GROUP BY cat ORDER BY cat")

    def timed(n):
        ts = []
        for _ in range(n):
            t0 = time.perf_counter()
            eng.sql(q)
            ts.append((time.perf_counter() - t0) * 1000)
        return {"p50": round(float(np.percentile(ts, 50)), 3),
                "p99": round(float(np.percentile(ts, 99)), 3)}

    eng.sql(q)  # compile warm-up
    quiesced = timed(iters)
    note(f"quiesced: {quiesced}")

    def mk_batch(i):
        r = np.random.default_rng(1000 + i)
        return [{"ts": int(pd.Timestamp("1994-01-01").value // 10**6)
                 + int(x), "cat": f"c{int(c):02d}", "v": int(v)}
                for x, c, v in zip(
                    r.integers(0, 86400_000 * 30, batch_rows),
                    r.integers(0, 32, batch_rows),
                    r.integers(0, 10_000, batch_rows))]

    # --- phase 2: sustained append throughput (compactor live)
    appended_batches = []
    sheds = 0
    t_start = time.perf_counter()
    i = 0
    while time.perf_counter() - t_start < run_s:
        b = mk_batch(i)
        try:
            eng.append("events", b)
            appended_batches.append(i)
        except IngestBackpressure:
            sheds += 1
            time.sleep(0.05)
        i += 1
    append_wall = time.perf_counter() - t_start
    n_appended = len(appended_batches) * batch_rows
    append_rps = n_appended / append_wall
    note(f"sustained append: {n_appended} rows in {append_wall:.2f}s "
         f"= {append_rps:,.0f} rows/s ({sheds} sheds)")

    # --- phase 3: query under ingest
    stop = threading.Event()

    def appender():
        j = 100_000
        while not stop.is_set():
            try:
                eng.append("events", mk_batch(j))
                appended_batches.append(j)
            except IngestBackpressure:
                time.sleep(0.05)
            j += 1

    th = threading.Thread(target=appender)
    th.start()
    try:
        under_ingest = timed(iters)
    finally:
        stop.set()
        th.join()
    note(f"under ingest: {under_ingest}")
    interference = round(
        under_ingest["p50"] / max(quiesced["p50"], 1e-3), 2)

    # --- phase 4: crash recovery + compaction
    snap = eng.ingest.snapshot()["tables"]["events"]
    eng.close()  # flush WAL deterministically, then abandon the engine
    total_appended = len(appended_batches) * batch_rows
    rec = Engine(mk_cfg())
    rec.config.ingest_auto_compact = False
    t0 = time.perf_counter()
    rec.register_table("events", base, time_column="ts",
                       block_rows=1 << 14, time_partition="month")
    recover_wall = time.perf_counter() - t0
    ev = [e for e in rec.runner.events.snapshot()
          if e["event"] == "wal_replay"]
    replay_ms = ev[0]["ms"] if ev else 0.0
    replay_rows = ev[0]["rows"] if ev else 0
    note(f"recovery: register+replay {recover_wall:.2f}s "
         f"(replay {replay_ms:.0f} ms for {replay_rows} rows)")
    t0 = time.perf_counter()
    rec.compact_now("events")
    compact_s = time.perf_counter() - t0

    # --- parity: recovered state == one-shot registration
    extra = pd.DataFrame(
        [r for i in sorted(set(appended_batches)) for r in mk_batch(i)])
    extra["ts"] = pd.to_datetime(extra["ts"], unit="ms")
    ref = Engine()
    ref.register_table("events",
                       pd.concat([base, extra], ignore_index=True),
                       time_column="ts", block_rows=1 << 14,
                       time_partition="month")
    dig = lambda f: hashlib.sha256(  # noqa: E731
        f.to_csv(index=False).encode()).hexdigest()
    parity_ok = dig(rec.sql(q)) == dig(ref.sql(q))
    note(f"recovery parity: {parity_ok}")

    # --- phase 5: checkpointed recovery (docs/DURABILITY.md) — the
    # same table, but with a durable checkpoint between the appends
    # and the crash: replay cost must drop from O(total appends) to
    # O(tail), independent of the pre-checkpoint volume
    full_replay_frames = ev[0]["records"] if ev else 0
    t0 = time.perf_counter()
    ck = rec.checkpoint_now("events")
    checkpoint_s = time.perf_counter() - t0
    tail_batches = 5
    for j in range(tail_batches):
        rec.append("events", mk_batch(900_000 + j))
    dig_before = dig(rec.sql(q))
    rec.close()
    t0 = time.perf_counter()
    rec2 = Engine(mk_cfg())
    rec2.config.ingest_auto_compact = False
    rec2.register_table("events", base, time_column="ts",
                        block_rows=1 << 14, time_partition="month")
    recover_ck_wall = time.perf_counter() - t0
    ev2 = [e for e in rec2.runner.events.snapshot()
           if e["event"] == "wal_replay"]
    loads = [e for e in rec2.runner.events.snapshot()
             if e["event"] == "store_load"]
    tail_frames = ev2[0]["records"] if ev2 else 0
    tail_replay_ms = ev2[0]["ms"] if ev2 else 0.0
    ck_parity_ok = dig(rec2.sql(q)) == dig_before
    note(f"checkpointed recovery: checkpoint {checkpoint_s:.2f}s "
         f"({ck.get('bytes', 0)} bytes, status {ck.get('status')}), "
         f"restart replayed {tail_frames} frames (full replay was "
         f"{full_replay_frames}) in {tail_replay_ms:.0f} ms; "
         f"parity {ck_parity_ok}")
    parity_ok = parity_ok and ck_parity_ok and bool(loads) \
        and tail_frames == tail_batches
    rec2.close()
    shutil.rmtree(wal_dir, ignore_errors=True)
    shutil.rmtree(store_dir, ignore_errors=True)

    out = {
        "metric": "ingest_append_rows_per_s",
        "value": round(append_rps, 1),
        "unit": "rows/s",
        "vs_baseline": None,
        "detail": {
            "base_rows": base_rows, "batch_rows": batch_rows,
            "wal_fsync": fsync, "run_s": run_s,
            "appended_rows_total": total_appended,
            "backpressure_sheds": sheds,
            "query_quiesced_ms": quiesced,
            "query_under_ingest_ms": under_ingest,
            "under_ingest_p50_interference_x": interference,
            "recovery": {
                "register_plus_replay_s": round(recover_wall, 3),
                "replay_ms": replay_ms, "replay_rows": replay_rows,
                "replay_rows_per_s": round(
                    replay_rows / max(replay_ms / 1000, 1e-6), 1),
                "compact_s": round(compact_s, 3)},
            # docs/DURABILITY.md: replay cost with a checkpoint on
            # disk is O(tail) — frames_replayed_tail vs
            # frames_replayed_full is the independence-from-volume
            # evidence (the tail is a fixed 5 batches regardless of
            # how much was appended before the checkpoint)
            "checkpointed_recovery": {
                "checkpoint_s": round(checkpoint_s, 3),
                "checkpoint_bytes": ck.get("bytes"),
                "wal_frames_truncated": ck.get(
                    "wal_frames_truncated"),
                "register_plus_replay_s": round(recover_ck_wall, 3),
                "replay_ms": tail_replay_ms,
                "frames_replayed_tail": tail_frames,
                "frames_replayed_full": full_replay_frames,
                "parity_ok": ck_parity_ok},
            "compactions": snap["compactions"],
            "wal_bytes_final": (snap["wal"] or {}).get("bytes"),
            "parity_ok": parity_ok,
        },
    }
    with open(os.path.join(REPO, "BENCH_INGEST.json"), "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))
    return 0 if parity_ok else 1


def _parse_args(argv=None):
    import argparse
    p = argparse.ArgumentParser(
        description="SSB benchmark: prints one JSON metric line "
                    "(worst-case p50 across the 13 SSB queries, or the "
                    "shared-scan batch throughput A/B with "
                    "--concurrency). Scale/iteration knobs are env vars "
                    "(SSB_ROWS, BENCH_ITERS, BENCH_RAM_CAP_GB, ...).")
    p.add_argument(
        "--concurrency", type=int, nargs="?", const=8, default=None,
        metavar="N",
        help="run the shared-scan batch throughput A/B with N "
             "concurrent clients (default 8) instead of the latency "
             "bench; banks BENCH_BATCH.json")
    p.add_argument(
        "--cache-mode", choices=("cold", "warm", "mixed"), default=None,
        metavar="MODE",
        help="run the semantic-result-cache bench instead of the "
             "latency bench: cold (caches cleared per run), warm "
             "(repeats served from cache), mixed (cold + warm + a "
             "fresh-ingest invalidation phase with parity in every "
             "state); banks BENCH_CACHE.json (docs/CACHING.md)")
    p.add_argument(
        "--cube-mode", choices=("off", "auto"), default=None,
        metavar="MODE",
        help="run the materialized-rollup-cube bench instead of the "
             "latency bench: off (base path only — the honest floor), "
             "auto (advisor-recommended cubes materialized from the "
             "bench's own workload profile, then base-vs-cube p50 with "
             "parity digests, materialization cost, and storage "
             "bytes); banks BENCH_CUBES.json (docs/CUBES.md)")
    p.add_argument(
        "--ingest-mode", action="store_true",
        help="run the real-time ingest bench instead of the latency "
             "bench: sustained WAL-durable append rows/s, query "
             "p50/p99 under ingest vs quiesced, crash-recovery replay "
             "time, and compaction cost, with sha256 recovery parity; "
             "banks BENCH_INGEST.json (docs/INGEST.md). Knobs: "
             "INGEST_BASE_ROWS, INGEST_BATCH_ROWS, INGEST_SECONDS, "
             "INGEST_WAL_FSYNC")
    p.add_argument(
        "--mesh", type=int, nargs="?", const=8, default=None,
        metavar="N",
        help="run the sharded-serving A/B instead of the latency "
             "bench: the 13 SSB queries on an N-chip mesh "
             "(jit + NamedSharding, interleaved placement, broker "
             "merge) vs one device over the same table, with sha256 "
             "result parity per query; banks MULTICHIP_r06.json "
             "(docs/TPU_NOTES.md). Without an accelerator the host "
             "platform is forced to N virtual CPU devices. Knobs: "
             "MULTICHIP_ROWS, BENCH_ITERS")
    p.add_argument(
        "--span-summary", action="store_true",
        help="emit per-query per-phase span timings (parse/plan/"
             "prepare/dispatch/host-transfer/assemble, from the "
             "obs.trace span tree) into the BENCH json detail as "
             "per_query_phase_p50_ms")
    p.add_argument(
        "--trace-out", type=str, default=None, metavar="PATH",
        help="write a Chrome-trace JSON (loads in Perfetto) of each "
             "SSB query's slowest timed iteration to PATH, so per-run "
             "profiles are banked next to the BENCH json "
             "(docs/OBSERVABILITY.md)")
    p.add_argument(
        "--inject-faults", type=int, nargs="?", const=3, default=None,
        metavar="N",
        help="after the clean timed runs, re-time each SSB query N "
             "times (default 3) with one injected dispatch fault per "
             "execution; banks per-query faulted p50 and the recovery "
             "overhead (faulted minus clean) into the BENCH json "
             "detail as fault_injection (docs/RESILIENCE.md)")
    p.add_argument(
        "--pipeline-depth", type=int, default=None, metavar="N",
        help="override EngineConfig.pipeline_depth for the latency "
             "bench (0 = serialized dispatch; default = engine "
             "default). The concurrency A/B lives in "
             "tools/bench_concurrency.py")
    args = p.parse_args(argv)
    if args.concurrency is not None and args.trace_out:
        p.error("--trace-out only applies to the latency bench; it is "
                "not written by the --concurrency throughput A/B")
    if args.cache_mode is not None and (args.concurrency is not None
                                        or args.trace_out
                                        or args.inject_faults):
        p.error("--cache-mode is its own bench; it does not combine "
                "with --concurrency/--trace-out/--inject-faults")
    if args.cube_mode is not None and (args.concurrency is not None
                                       or args.cache_mode is not None
                                       or args.trace_out
                                       or args.inject_faults):
        p.error("--cube-mode is its own bench; it does not combine "
                "with the other modes")
    if args.ingest_mode and (args.concurrency is not None
                             or args.cache_mode is not None
                             or args.cube_mode is not None
                             or args.trace_out or args.inject_faults):
        p.error("--ingest-mode is its own bench; it does not combine "
                "with the other modes")
    if args.mesh is not None and (args.concurrency is not None
                                  or args.cache_mode is not None
                                  or args.cube_mode is not None
                                  or args.ingest_mode
                                  or args.trace_out
                                  or args.inject_faults):
        p.error("--mesh is its own bench; it does not combine with "
                "the other modes")
    return args


if __name__ == "__main__":
    args = _parse_args()
    if args.mesh is not None:
        sys.exit(_mesh_main(args.mesh))
    if args.ingest_mode:
        sys.exit(_ingest_main())
    if args.cube_mode is not None:
        sys.exit(_cube_main(args.cube_mode))
    if args.cache_mode is not None:
        sys.exit(_cache_main(args.cache_mode))
    if args.concurrency is not None:
        sys.exit(_concurrency_main(args.concurrency))
    main(span_summary=args.span_summary, inject_faults=args.inject_faults,
         trace_out=args.trace_out, pipeline_depth=args.pipeline_depth)
